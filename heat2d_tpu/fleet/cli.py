"""``heat2d-tpu-fleet`` — drive a supervised worker pool, optionally
under chaos, and prove the fleet invariants from outside.

The soak (``--soak S``) sustains a closed-loop load of ``--concurrency``
outstanding requests over a rotating set of signatures for S seconds.
With ``--chaos``, ``--kill K`` workers are hard-killed at the soak's
midpoint (the supervisor must detect, fail over, and restart them).
After the load drains, the CLI asserts the chaos-soak acceptance
criteria and exits nonzero if any fail:

1. **Zero incorrect results** — every distinct request is re-solved by
   a single-worker ORACLE (an in-process ``SolveServer``) and every
   fleet response must match it bitwise.
2. **Nothing silently lost** — submitted == completed + structured
   ``Rejected`` (and under default sizing, zero rejections).
3. **Throughput recovers** — after the kill, the completion rate over
   a sliding window must return to within ``--recovery-margin``
   (default 20%) of the pre-kill steady state. Recovery is MEASURED,
   not scheduled: the load keeps running until the bar clears (the
   time-to-recovery is reported) or 3x the nominal soak elapses
   (a failure).
4. **Clean exit** — every worker drains and exits 0 at shutdown.

``--metrics-out`` writes the registry JSONL + a ``kind="fleet"`` run
record (soak phases, throughput windows, worker deaths/restarts,
replay counts). CI's ``fleet-soak`` job runs exactly this on CPU with
3 workers and one mid-load kill.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

from heat2d_tpu.analysis.locks import AuditedLock


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-fleet",
        description="supervised multi-worker serving pool with "
                    "chaos-proven failover (docs/FLEET.md)")
    p.add_argument("--workers", type=int, default=3,
                   help="worker subprocesses in the pool")
    p.add_argument("--soak", type=float, default=None, metavar="S",
                   help="run the sustained-load soak for S seconds "
                        "and assert the fleet invariants")
    p.add_argument("--chaos", action="store_true",
                   help="with --soak: hard-kill --kill workers at the "
                        "soak midpoint (failover + restart must absorb "
                        "it)")
    p.add_argument("--kill", type=int, default=1, metavar="K",
                   help="workers to kill with --chaos (k of N)")
    p.add_argument("--concurrency", type=int, default=8,
                   help="outstanding requests in the closed loop")
    p.add_argument("--nx", type=int, default=16)
    p.add_argument("--ny", type=int, default=16)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--signatures", type=int, default=2,
                   help="distinct compiled signatures in the request "
                        "mix (steps, steps+1, ...)")
    p.add_argument("--recovery-margin", type=float, default=0.2,
                   help="allowed post-restart throughput drop vs the "
                        "pre-kill window (0.2 = within 20%%)")
    p.add_argument("--window", type=float, default=None, metavar="S",
                   help="throughput measurement window (default: a "
                        "third of the soak)")
    p.add_argument("--heartbeat-timeout", type=float, default=2.0)
    p.add_argument("--timeout", type=float, default=60.0,
                   help="per-request fleet deadline")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write telemetry JSONL (fleet_* families + the "
                        "kind='fleet' run record)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="arm fleet-wide distributed tracing AND the "
                        "workers' crash flight recorders: the router "
                        "and every worker write span JSONL into DIR "
                        "(workers inherit HEAT2D_TRACE_DIR/"
                        "HEAT2D_FLIGHT_DIR through the supervisor); "
                        "merge with heat2d-tpu-trace DIR. A chaos-"
                        "killed worker leaves a digest-sidecar'd "
                        "post-mortem of its last seconds")
    p.add_argument("--worker-env", action="append", default=[],
                   metavar="SLOT:KEY=VAL",
                   help="extra env for ONE worker slot (repeatable) — "
                        "e.g. 0:HEAT2D_CHAOS_WORKER_KILL_AFTER=5 aims "
                        "a chaos self-kill at worker 0 (unlike the "
                        "supervisor-side --chaos SIGKILL, a self-kill "
                        "flushes the worker's flight recorder)")
    p.add_argument("--slo-p99", type=float, default=None, metavar="S",
                   help="per-signature p99 latency target; evaluation "
                        "lands in the run record's 'slo' rows and the "
                        "slo_* gauges (docs/OBSERVABILITY.md)")
    p.add_argument("--slo-error-budget", type=float, default=0.001,
                   metavar="F",
                   help="allowed failure fraction per signature")
    p.add_argument("--control", action="store_true",
                   help="arm the SLO-driven control plane beside the "
                        "soak (docs/CONTROL.md): a BurnWindow watches "
                        "per-signature burn and sheds/retunes before "
                        "the breaker trips; workers serve under the "
                        "control db directory's validated tuning db")
    p.add_argument("--control-db", default=None, metavar="DIR",
                   help="directory for the control plane's "
                        "validated.json / candidate.json tuning dbs "
                        "(default: a temp dir)")
    p.add_argument("--control-rollout", action="store_true",
                   help="with --control: at the soak midpoint, stage "
                        "a candidate db for the hottest signature "
                        "(simulated measurement backend) and run one "
                        "safe rollout — canary, bitwise parity, "
                        "observation, promote or auto-revert — while "
                        "the load keeps running")
    p.add_argument("--control-bad-candidate", action="store_true",
                   help="inject a deliberately-bad candidate: the "
                        "canary's one-generation env overlay carries "
                        "HEAT2D_CHAOS_SLOW_WORKER_S, so the rollout "
                        "MUST measure the regression and auto-revert "
                        "with bitwise post-revert parity (the CLI "
                        "fails otherwise)")
    p.add_argument("--control-storm-phase", default=None,
                   choices=["canary", "parity", "observe", "promote"],
                   help="arm a chaos kill storm (every worker hard-"
                        "killed) to land when the rollout reaches "
                        "this window; the CLI then asserts no worker "
                        "generation ever served a non-validated "
                        "config")
    p.add_argument("--control-observe", type=float, default=2.0,
                   metavar="S",
                   help="rollout observation window (paired probes + "
                        "windowed SLO burn)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a JAX platform for the workers "
                        "(default cpu: the soak is a logic gate, not a "
                        "bench)")
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"])
    return p


def _requests(args, n: int):
    """The soak's request stream (a generator): ``n`` requests over
    ``--signatures`` distinct compiled signatures with rotating
    diffusivities. The pool repeats with period 256 per signature —
    bounded so the oracle can verify every distinct hash — which is
    why ``run_soak`` disables every result cache: the repeats must
    re-solve, or the throughput gate would measure cache service."""
    from heat2d_tpu.serve.schema import SolveRequest
    for i in range(n):
        yield SolveRequest(
            nx=args.nx, ny=args.ny,
            steps=args.steps + (i % args.signatures),
            cx=0.05 + 0.0003 * (i % 256), cy=0.1, method="jnp")


def _parse_worker_env(specs) -> dict:
    """--worker-env SLOT:KEY=VAL flags -> per_worker_env dict."""
    out: dict = {}
    for spec in specs:
        try:
            slot, kv = spec.split(":", 1)
            key, val = kv.split("=", 1)
            out.setdefault(int(slot), {})[key] = val
        except ValueError:
            raise SystemExit(f"bad --worker-env {spec!r} "
                             f"(want SLOT:KEY=VAL)") from None
    return out


def run_soak(args, registry) -> int:
    from heat2d_tpu.fleet.router import FleetServer
    from heat2d_tpu.serve.schema import Rejected

    failures = []
    events = []                 # (t, "completed" | rejected-code)
    ev_lock = AuditedLock("fleet.cli.events")
    responses = {}              # content_hash -> result bytes
    env = ({"JAX_PLATFORMS": args.platform} if args.platform
           else {"JAX_PLATFORMS": "cpu"})

    # -- control plane setup (docs/CONTROL.md) -------------------------- #
    control = args.control or args.control_rollout
    validated_path = candidate_path = None
    if control:
        import tempfile
        cdir = args.control_db or tempfile.mkdtemp("heat2d-control")
        os.makedirs(cdir, exist_ok=True)
        validated_path = os.path.join(cdir, "validated.json")
        candidate_path = os.path.join(cdir, "candidate.json")
        # every worker serves under the VALIDATED db path (a missing
        # file degrades to "no db"); rollouts hand the candidate path
        # to a canary via a one-generation env overlay only
        env["HEAT2D_TUNE_DB"] = validated_path
    if args.control_storm_phase:
        from heat2d_tpu.resil import chaos
        chaos.install(chaos.ChaosConfig(
            rollout_kill_phase=args.control_storm_phase,
            rollout_kills=0), registry=registry)

    fleet = FleetServer(
        workers=args.workers, registry=registry,
        default_timeout=args.timeout,
        heartbeat_timeout=args.heartbeat_timeout,
        # ALL result caches are OFF for the soak: the request pool
        # cycles (bounded so the oracle can verify every distinct
        # hash), and either the router-side shared cache or the
        # workers' own LRUs would absorb the repeats — the throughput
        # windows must measure the SOLVE path the chaos is aimed at,
        # not cache service (which has its own tests).
        cache_size=0, worker_cache_size=0,
        env=env,
        per_worker_env=_parse_worker_env(args.worker_env))
    killed = []
    submitted = 0
    sem = threading.Semaphore(args.concurrency)

    def on_done(fut, req):
        import numpy as np
        now = time.monotonic()
        try:
            res = fut.result()
            with ev_lock:
                events.append((now, "completed"))
                responses.setdefault(req.content_hash(),
                                     np.asarray(res.u).tobytes())
                if responses[req.content_hash()] != \
                        np.asarray(res.u).tobytes():
                    failures.append(
                        f"divergent responses for {req.content_hash()}")
        except Rejected as e:
            with ev_lock:
                events.append((now, f"rejected_{e.code}"))
        except Exception as e:  # noqa: BLE001 — a soak reports, always
            with ev_lock:
                events.append((now, f"error:{e!r}"))
        sem.release()

    print(f"# fleet soak: {args.workers} workers, {args.soak:.0f}s, "
          f"concurrency {args.concurrency}"
          + (f", killing {args.kill} at midpoint" if args.chaos else "")
          + (", control plane armed" if control else ""))
    plane = None
    rollout_thread = None
    rollout_out: dict = {}
    control_extra = None
    with fleet:
        # Warmup OUTSIDE the measured window: every signature compiles
        # its padded batch programs on every worker-reachable path, so
        # the pre-kill window measures steady-state serving, not
        # compilation (the throughput-recovery gate needs a real
        # baseline to compare against).
        warm = [fleet.submit(r) for r in
                (dataclasses.replace(req, cx=0.9 + 0.0003 * j)
                 for j, req in enumerate(_requests(
                     args, args.signatures * max(args.concurrency, 8))))]
        for f in warm:
            try:
                f.result(timeout=args.timeout + 60)
            except Exception:   # noqa: BLE001 — warmup is best-effort
                pass
        if control:
            from heat2d_tpu.control import ControlPlane, Retuner
            from heat2d_tpu.obs import slo as _slo
            plane = ControlPlane(
                fleet,
                policy=_slo.SLOPolicy(
                    latency_p99_s=args.slo_p99 or 30.0,
                    error_budget=args.slo_error_budget),
                retuner=Retuner(fleet, candidate_path=candidate_path,
                                validated_path=validated_path),
                registry=registry).start()
        t_start = time.monotonic()
        kill_at = t_start + args.soak / 2 if args.chaos else None
        rollout_at = (t_start + args.soak / 2
                      if args.control_rollout else None)
        window = args.window or max(1.0, args.soak / 3)
        reqs = iter(_requests(args, 10 ** 9))
        t_rec = None        # when the fleet was whole-and-warm again
        pre = post = None   # rps windows
        t_thr = None        # when throughput was back within margin
        last_check = 0.0
        while True:
            now = time.monotonic()
            if (killed and t_rec is None
                    and fleet.sup.deaths >= len(killed)
                    and fleet.sup.restarts >= len(killed)
                    and len(fleet.sup.alive_slots()) == args.workers
                    and not fleet._cold):
                t_rec = now
                print(f"# t+{now - t_start:.1f}s: fleet recovered "
                      f"({args.workers} workers alive and warm)")
            if (pre is not None and t_thr is None
                    and now >= kill_at + window   # window all post-kill
                    and now - last_check >= 0.25):
                # the recovery probe: completion rate over the sliding
                # last window, against the pre-kill baseline
                last_check = now
                with ev_lock:
                    r = _rate(events, 0.0, now - window, now)
                if r >= (1.0 - args.recovery_margin) * pre:
                    t_thr, post = now, r
                    print(f"# t+{now - t_start:.1f}s: throughput "
                          f"recovered ({r:.1f} rps vs {pre:.1f} "
                          f"pre-kill)")
            if (rollout_at is not None and rollout_thread is None
                    and now >= rollout_at):
                rollout_at = None
                rollout_thread = _start_rollout(
                    args, plane, validated_path, candidate_path,
                    rollout_out, failures)
            if now - t_start >= args.soak:
                # "throughput recovered after restart" is measured, not
                # scheduled: under --chaos the load keeps running until
                # the sliding window clears the recovery bar (hard-
                # capped at 3x the nominal soak, reported as a failure)
                chaos_done = (not args.chaos
                              or (t_thr is not None and t_rec is not None)
                              or now - t_start >= 3 * args.soak)
                # a mid-soak rollout keeps its observation probes under
                # live load: the loop runs until it settles (capped)
                rollout_done = (rollout_thread is None
                                or not rollout_thread.is_alive()
                                or now - t_start >= 6 * args.soak)
                if chaos_done and rollout_done:
                    break
            if (kill_at is not None and not killed
                    and now >= kill_at):
                with ev_lock:
                    pre = _rate(events, t_start, kill_at - t_start
                                - window, kill_at - t_start)
                for k in range(args.kill):
                    fleet.sup.kill_worker(k)
                    killed.append(k)
                print(f"# t+{now - t_start:.1f}s: killed "
                      f"worker(s) {killed} (pre-kill {pre:.1f} rps)")
            if not sem.acquire(timeout=0.1):
                continue
            req = next(reqs)
            submitted += 1
            fleet.submit(req).add_done_callback(
                lambda f, r=req: on_done(f, r))
        if rollout_thread is not None:
            rollout_thread.join(timeout=3 * args.soak + 120)
            if rollout_thread.is_alive():
                failures.append("control rollout did not finish")
        # drain: wait for every outstanding slot back
        for _ in range(args.concurrency):
            sem.acquire(timeout=args.timeout + 30)
        if plane is not None:
            plane.stop()
            control_extra = plane.summary()
            control_extra["validated_path"] = validated_path
            control_extra["candidate_path"] = candidate_path
            # what every CURRENT worker reports serving, pre-shutdown
            control_extra["workers_tune"] = {
                str(s): (fleet.sup.worker_info(s) or {}).get("tune")
                for s in fleet.sup.alive_slots()}
        deaths, restarts = fleet.sup.deaths, fleet.sup.restarts
        alive = len(fleet.sup.alive_slots())
        clean = fleet.stop()
    if args.control_storm_phase:
        from heat2d_tpu.resil import chaos
        chaos.uninstall()

    answered = len(events)
    completed = sum(1 for _t, o in events if o == "completed")
    rejected = answered - completed
    if answered != submitted:
        failures.append(f"silent loss: {submitted} submitted but only "
                        f"{answered} answered")
    if completed == 0:
        failures.append("no request completed")
    errors = [o for _t, o in events if o.startswith("error:")]
    if errors:
        failures.append(f"{len(errors)} unstructured errors, e.g. "
                        f"{errors[0]}")

    # -- oracle: every distinct request, bitwise ----------------------- #
    mismatches = _oracle_check(args, responses)
    if mismatches:
        failures.append(f"{mismatches} responses differ bitwise from "
                        f"the single-worker oracle")

    # -- throughput windows -------------------------------------------- #
    summary = {
        "workers": args.workers, "soak_s": args.soak,
        "submitted": submitted, "completed": completed,
        "rejected": rejected, "distinct": len(responses),
        "deaths": deaths, "restarts": restarts,
        "replays": fleet.replays, "alive_at_end": alive,
        "clean_exit": clean, "killed": killed,
    }
    if args.chaos:
        if post is None:        # never cleared the bar: report the tail
            t_end = events[-1][0] if events else time.monotonic()
            post = _rate(events, 0.0, t_end - window, t_end)
        summary.update(
            pre_kill_rps=round(pre or 0.0, 2),
            post_restart_rps=round(post, 2), window_s=window,
            restart_recovery_s=(None if t_rec is None
                                else round(t_rec - kill_at, 2)),
            throughput_recovery_s=(None if t_thr is None
                                   else round(t_thr - kill_at, 2)))
        if registry is not None:
            registry.gauge("fleet_throughput_rps", pre or 0.0,
                           window="pre_kill")
            registry.gauge("fleet_throughput_rps", post,
                           window="post_restart")
            if t_thr is not None:
                registry.gauge("fleet_recovery_s", t_thr - kill_at)
        if not pre:
            failures.append("no pre-kill steady state measured — the "
                            "recovery gate would be vacuous (soak too "
                            "short or workers never warmed)")
        if t_rec is None:
            failures.append("fleet never returned to full strength "
                            "(no recovery point observed)")
        if deaths < len(killed):
            failures.append(f"killed {len(killed)} workers but only "
                            f"{deaths} deaths detected")
        if restarts < len(killed):
            failures.append(f"no restart after kill ({restarts} < "
                            f"{len(killed)})")
        if pre and t_thr is None:
            failures.append(
                f"throughput did not recover within {3 * args.soak:.0f}"
                f"s: {post:.1f} rps vs {pre:.1f} pre-kill (margin "
                f"{args.recovery_margin})")
    if not clean:
        failures.append("supervisor shutdown was not clean")

    # -- control-plane acceptance (docs/CONTROL.md) --------------------- #
    if control_extra is not None:
        from heat2d_tpu.tune.db import TuningDB
        if not control_extra.get("no_unvalidated_serving"):
            failures.append(
                "control: a non-rollout worker generation served a "
                "non-validated config: "
                f"{control_extra.get('unvalidated_serving')}")
        oc = rollout_out.get("outcome")
        control_extra["rollout_outcome"] = oc
        if args.control_rollout and oc is None:
            failures.append("control: the rollout never produced an "
                            "outcome")
        elif args.control_bad_candidate:
            if not (oc or "").startswith("reverted"):
                failures.append(f"control: the deliberately-bad "
                                f"candidate was NOT auto-reverted "
                                f"(outcome {oc})")
            elif rollout_out.get("post_revert_parity") is not True:
                failures.append("control: post-revert answers were "
                                "not bitwise-identical to the "
                                "pre-rollout baseline")
        elif args.control_storm_phase and (oc or "").startswith(
                "reverted"):
            if rollout_out.get("post_revert_parity") is not True:
                failures.append("control: storm revert without a "
                                "bitwise post-revert parity proof")
        elif args.control_rollout and not args.control_storm_phase:
            if oc != "promoted":
                failures.append(f"control: a healthy candidate did "
                                f"not promote (outcome {oc})")
            else:
                vdb = TuningDB(validated_path)
                if not (vdb.validated and vdb.epoch
                        == rollout_out.get("epoch")):
                    failures.append(
                        f"control: promote did not advance the "
                        f"validated db (epoch {vdb.epoch}, validated "
                        f"{vdb.validated})")
        summary["control"] = {
            "rollout_outcome": oc,
            "no_unvalidated_serving":
                control_extra.get("no_unvalidated_serving"),
            "decisions": len(control_extra.get("decisions", [])),
        }

    print(f"# soak summary: {json.dumps(summary)}")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    _write_metrics(args, registry, dict(summary, failures=failures),
                   control=control_extra)
    print("fleet soak " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


def _start_rollout(args, plane, validated_path, candidate_path,
                   out, failures):
    """Stage a candidate for the hottest signature (simulated
    measurement backend — the rollout machinery, not kernel speed, is
    under test on CPU) and run one safe rollout on a thread beside the
    live load. Appends to ``failures`` / updates ``out`` in place."""
    from heat2d_tpu.control import RolloutConfig

    staged = None
    for sig, _n in plane.retuner.hot_signatures():
        staged = plane.retuner.stage_candidate(sig)
        if staged is not None:
            break
    if staged is None:
        failures.append("control rollout: no tunable hot signature "
                        "to stage")
        return None
    extra = ({"HEAT2D_CHAOS_SLOW_WORKER_S": "0.5"}
             if args.control_bad_candidate else {})
    cfg = RolloutConfig(
        candidate_path=candidate_path, validated_path=validated_path,
        probe_spec={"nx": args.nx, "ny": args.ny, "steps": args.steps,
                    "cx": 0.123, "cy": 0.1, "method": "jnp"},
        observe_s=args.control_observe,
        probe_timeout=args.timeout,
        extra_canary_env=extra)
    print(f"# control: staged candidate epoch {staged['epoch']} for "
          f"{staged['signature']}; starting rollout"
          + (" (bad-candidate injection armed)" if extra else ""))

    def _run():
        out.update(plane.run_rollout(cfg))
        print(f"# control: rollout outcome {out.get('outcome')}")

    t = threading.Thread(target=_run, name="heat2d-control-rollout",
                         daemon=True)
    t.start()
    return t


def _rate(events, t_start: float, lo: float, hi: float) -> float:
    """Completions per second inside the (lo, hi] soak-relative
    window."""
    if hi <= lo:
        return 0.0
    n = sum(1 for t, o in events
            if o == "completed" and lo < t - t_start <= hi)
    return n / (hi - lo)


def _oracle_check(args, responses) -> int:
    """Re-solve every distinct request on ONE in-process server and
    count bitwise mismatches against the fleet's answers."""
    import numpy as np

    from heat2d_tpu.serve.schema import SolveRequest
    from heat2d_tpu.serve.server import SolveServer

    todo = dict(responses)
    mismatches = 0
    with SolveServer(registry=None) as oracle:
        # regenerate the request stream and solve each distinct hash
        for req in _requests(args, 10 ** 6):
            h = req.content_hash()
            if h not in todo:
                if not todo:
                    break
                continue
            want = todo.pop(h)
            got = np.asarray(
                oracle.solve(req, timeout=120).u).tobytes()
            if got != want:
                mismatches += 1
    return mismatches + len(todo)


def _write_metrics(args, registry, extra, control=None) -> None:
    from heat2d_tpu.obs.record import write_run_jsonl
    if args.slo_p99 is not None and registry is not None:
        from heat2d_tpu.obs import slo
        slo.stamp_record(extra, slo.evaluate(
            registry, prefix="fleet",
            default=slo.SLOPolicy(latency_p99_s=args.slo_p99,
                                  error_budget=args.slo_error_budget)))
    if args.trace_dir:
        from heat2d_tpu.obs import flight, tracing
        t = tracing.tracer()
        extra["trace"] = {
            "dir": args.trace_dir,
            "router_spans": t.spans_emitted if t is not None else 0,
            "postmortems": len(flight.find_postmortems(args.trace_dir)),
        }
    # the control plane's decisions/rollouts/invariant ride as their
    # own kind="control" record beside the fleet record
    write_run_jsonl(registry, args.metrics_out, "fleet", extra,
                    more=[("control", control)] if control else ())


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.log_level:
        import logging
        logging.basicConfig(
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
        logging.getLogger("heat2d_tpu").setLevel(
            getattr(logging, args.log_level.upper()))
    # The router/oracle process stays on CPU unless told otherwise —
    # workers get their platform via env (run_soak).
    os.environ.setdefault("JAX_PLATFORMS", args.platform or "cpu")
    if args.trace_dir:
        # Router tracer here; workers inherit the campaign through the
        # environment (the supervisor copies os.environ into each
        # worker): every process writes spans into the ONE directory,
        # and each worker arms a flight recorder the chaos kill points
        # will flush (docs/OBSERVABILITY.md).
        # explicit flag wins over any stale env vars: if setdefault
        # kept an old HEAT2D_TRACE_DIR, the workers (which inherit the
        # env) would write spans into a DIFFERENT directory than the
        # router traces and --require-postmortem checks — a silently
        # split campaign
        os.environ["HEAT2D_TRACE_DIR"] = args.trace_dir
        os.environ["HEAT2D_FLIGHT_DIR"] = args.trace_dir
        from heat2d_tpu.obs import tracing
        tracing.install(tracing.Tracer(args.trace_dir, service="router"))

    if ((args.control_storm_phase or args.control_bad_candidate)
            and not args.control_rollout):
        # without a rollout there is no storm window and no canary to
        # poison — a soak that "passed" would prove nothing
        print("--control-storm-phase/--control-bad-candidate require "
              "--control-rollout (they act on a live rollout)",
              file=sys.stderr)
        return 2
    from heat2d_tpu.obs import MetricsRegistry
    registry = MetricsRegistry()
    if args.soak is not None:
        return run_soak(args, registry)
    print("nothing to do: pass --soak S (optionally --chaos) — the "
          "fleet embeds in-process via heat2d_tpu.fleet.FleetServer; "
          "docs/FLEET.md", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
