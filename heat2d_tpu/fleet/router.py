"""Fleet router — signature-affine distribution with chaos-proven
failover, a shared content-addressed cache, and tenant quotas.

``FleetServer`` is the fleet's front door: the same ``submit() ->
Future`` contract as a single ``SolveServer``, served by N worker
subprocesses behind a ``Supervisor``. The layers, outermost first:

1. **Shared cache + fleet single-flight.** The sha256 content hash is
   already the distribution key, so one bounded LRU in the router
   process covers ALL workers: any worker's answer warms every future
   caller, and the cache survives worker restarts — the fleet's warm
   state lives above the blast radius of any one process. Identical
   in-flight requests coalesce fleet-wide onto one dispatch.
2. **Admission: quotas, capacity, breaker.** Per-tenant
   ``TenantPolicy`` (max in-flight + priority class: standard tenants
   shed at the high watermark, ``priority=0`` tenants may use the
   reserved headroom), a global in-flight cap, and the resil
   ``DegradedMode`` breaker — worker deaths are its failure signal, so
   a fleet in a crash loop sheds fresh compute while cache hits keep
   answering. Cache hits and coalesced followers bypass quota/capacity
   entirely: they cost no launch, and shedding an answer the fleet
   already owns is never load shedding.
3. **Routing.** Rendezvous (highest-random-weight) hashing of the
   compiled signature over the ALIVE workers: each signature sticks to
   one worker (its batcher buckets fill, its compile cache stays warm)
   and a death remaps ONLY the dead worker's share — survivors keep
   their warm signatures.
4. **Failover.** Every dispatch is tracked in flight. When the
   supervisor declares a worker dead, its in-flight requests REPLAY to
   a survivor under a fresh wire id (at most ``max_replays`` hops,
   then a structured ``Rejected("worker_lost")``). Solves are
   deterministic, so a replayed answer is bitwise the answer the dead
   worker would have given; the single-flight future resolves exactly
   once, so a client sees at most a latency blip — never a lost or
   duplicated answer. With no workers alive, requests PARK and flush
   the moment a restarted worker reports ready; fleet-level deadlines
   expire both parked and in-flight stragglers into
   ``Rejected("timeout")``.
5. **Warm restart.** A RESTARTED worker (never a first spawn) rejoins
   in two phases: on ``ready`` the router replays the fleet's HOT
   SIGNATURES to it as warmup events (off the client path), and the
   slot stays out of routing until the worker reports warm — one
   compiled program per hot signature; wider batch capacities compile
   on demand (fleet/worker._warm_signature on why not the full
   ladder). The compiled-program working set re-warms from the fleet's live
   state before client requests can stall behind a fully cold worker —
   the serving analogue of ``resil``'s restart-from-checkpoint (the
   per-solve checkpoints themselves don't apply at serve timescales;
   the warm state worth restoring is the compile cache, plus the
   router-side shared result cache that never died). When every alive
   worker is still cold (a full-fleet restart), routing falls back to
   cold workers — a slow answer beats a parked one.

Metric families (docs/FLEET.md has the table): ``fleet_requests_total
{outcome}``, ``fleet_e2e_latency_s``, ``fleet_cache_*``,
``fleet_coalesced_total``, ``fleet_inflight`` / ``fleet_parked``
gauges, ``fleet_quota_rejected_total{tenant}``,
``fleet_failover_replays_total``, ``fleet_workers_alive``,
``fleet_worker_deaths_total{cause}``, ``fleet_worker_restarts_total``,
``fleet_degraded`` / ``fleet_breaker_trips_total``,
``fleet_shed_watermark`` (the control plane's pre-emptive-shed
actuator — docs/CONTROL.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import math
import random
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from heat2d_tpu.analysis.locks import AuditedLock, guarded_by
from heat2d_tpu.fleet import wire
from heat2d_tpu.fleet.supervisor import Supervisor, WorkerGone
from heat2d_tpu.obs import tracing
from heat2d_tpu.resil.retry import DegradedMode, RetryPolicy
from heat2d_tpu.serve.cache import ResultCache, SingleFlight
from heat2d_tpu.serve.schema import Rejected, SolveRequest, SolveResult
from heat2d_tpu.serve.server import _outcome_of, coalesced_future
from heat2d_tpu.serve.server import failed_future as _failed

log = logging.getLogger("heat2d_tpu.fleet")

#: fraction of global capacity standard-priority tenants may fill; the
#: headroom above it is reserved for priority-0 (critical) tenants
HIGH_WATERMARK = 0.8

#: most-recent compiled signatures replayed to a restarted worker as
#: compile warmup before it takes client traffic
MAX_HOT_SIGNATURES = 32


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """Admission policy for one tenant. ``priority`` 0 is critical —
    admitted up to the full global capacity; standard tenants (>= 1)
    shed once the high watermark is reached, so a burst from a batch
    tenant cannot starve interactive traffic."""

    max_inflight: int = 64
    priority: int = 1

    def __post_init__(self):
        if self.max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {self.max_inflight}")
        if self.priority < 0:
            raise ValueError(
                f"priority must be >= 0, got {self.priority}")


def _end_wire(rec, **attrs) -> None:
    """Close the record's open wire span, if any (idempotent)."""
    ws, rec.wire_span = rec.wire_span, None
    if ws is not None:
        ws.end(**attrs)


def route_signature(sig: str, alive: List[int]) -> int:
    """Rendezvous hashing: the alive worker with the highest
    hash(sig, worker) wins. Deterministic, coordination-free, and
    minimally disruptive — removing a worker remaps only the
    signatures it owned."""
    if not alive:
        raise ValueError("no alive workers to route to")
    return max(alive, key=lambda w: hashlib.sha256(
        f"{sig}|{w}".encode()).digest())


@dataclasses.dataclass
class _Inflight:
    """One dispatched request: everything needed to answer it — or to
    replay it somewhere else. ``warmup`` records belong to a restarted
    worker's rejoin phase: no client future waits on them, they are
    never replayed, and their answers are discarded. ``probe`` records
    are the control plane's targeted dispatches (``FleetServer.
    probe``): pinned to ONE slot, bypassing cache/single-flight/
    quotas, never replayed — their answer (or structured failure)
    resolves ``fut`` directly."""
    key: Optional[str]          # content hash (cache / flight key)
    sig: str                    # signature string (routing key)
    tenant: str
    req_dict: dict
    t0: float
    deadline: Optional[float]
    slot: Optional[int] = None
    rid: Optional[int] = None
    replays: int = 0
    warmup: bool = False
    probe: bool = False
    fut: "object" = None        # probe-only: the caller's future
    #: tracing (obs/tracing.py): the request's root span, and the
    #: OPEN wire span of the current dispatch (a replay closes the old
    #: one and opens a fresh one — one wire span per hop). Warmup
    #: records never trace: they are the router's own business, not a
    #: request's causal chain.
    span: "object" = None
    wire_span: "object" = None


@guarded_by("_lock", "_parked", "_next_rid", "_total_inflight",
            "_stopped", "_shed_watermark")
class FleetServer:
    """N supervised workers behind one ``submit()``. See the module
    docstring for the layer map."""

    def __init__(self, workers: int = 2, *,
                 max_batch: int = 8, max_delay: float = 0.005,
                 queue_depth: int = 256, worker_cache_size: int = 256,
                 worker_timeout: float = 30.0,
                 cache_size: int = 512,
                 default_timeout: Optional[float] = 30.0,
                 max_inflight: int = 256,
                 quotas: Optional[Dict[str, TenantPolicy]] = None,
                 default_policy: Optional[TenantPolicy] = None,
                 max_replays: int = 2,
                 heartbeat_interval: float = 0.25,
                 heartbeat_timeout: float = 2.0,
                 restart_policy: Optional[RetryPolicy] = None,
                 restart_rng: Optional[random.Random] = None,
                 max_restarts: Optional[int] = None,
                 breaker: Optional[DegradedMode] = None,
                 registry=None, env: Optional[dict] = None,
                 per_worker_env: Optional[Dict[int, dict]] = None):
        if registry is None:
            from heat2d_tpu.obs import get_registry
            registry = get_registry()
        self.registry = registry
        self.default_timeout = default_timeout
        self.max_inflight = max_inflight
        self.quotas = dict(quotas or {})
        #: the unnamed tenant is critical by default: reservations are
        #: something operators opt INTO by naming lower-priority tenants
        self.default_policy = (TenantPolicy(max_inflight=max_inflight,
                                            priority=0)
                               if default_policy is None
                               else default_policy)
        self.max_replays = max_replays
        self.cache = ResultCache(cache_size, registry=registry,
                                 prefix="fleet_cache")
        self.flight = SingleFlight(registry=registry,
                                   counter="fleet_coalesced_total")
        self.breaker = (DegradedMode(registry=registry,
                                     metric_prefix="fleet")
                        if breaker is None else breaker)
        self.sup = Supervisor(
            workers,
            worker_args=["--max-batch", str(max_batch),
                         "--max-delay", str(max_delay),
                         "--queue-depth", str(queue_depth),
                         "--cache-size", str(worker_cache_size),
                         "--timeout", str(worker_timeout)],
            env=env, per_worker_env=per_worker_env,
            heartbeat_interval=heartbeat_interval,
            heartbeat_timeout=heartbeat_timeout,
            restart_policy=restart_policy, restart_rng=restart_rng,
            max_restarts=max_restarts, registry=registry,
            on_response=self._on_response,
            on_worker_lost=self._on_worker_lost,
            on_worker_ready=self._on_worker_ready,
            on_worker_retiring=self._on_worker_retiring,
            on_tick=self._expire_overdue)
        self._lock = AuditedLock("fleet.router")
        self._records: Dict[int, _Inflight] = {}
        self._parked: List[_Inflight] = []
        self._next_rid = 0
        self._tenant_inflight: Dict[str, int] = {}
        self._total_inflight = 0
        #: sig -> an example spec dict (the fleet's hot-signature set,
        #: replayed to restarted workers as compile warmup)
        self._hot: Dict[str, dict] = {}
        #: slots that are ready but still warming (not routable unless
        #: every alive slot is cold)
        self._cold: set = set()
        #: slot -> outstanding warmup rids
        self._warming: Dict[int, set] = {}
        #: slots fenced for retirement (``_on_worker_retiring`` — fired
        #: by the supervisor BEFORE the drain begins): never routable
        #: again, not even under the all-cold fallback. Retired slot
        #: indices are never reused, so the set only grows.
        self._retiring: set = set()
        #: control-plane override of HIGH_WATERMARK (pre-emptive
        #: shedding under sustained SLO burn — docs/CONTROL.md); None
        #: means the static default
        self._shed_watermark: Optional[float] = None
        self._stopped = False
        self.replays = 0

    # -- lifecycle ----------------------------------------------------- #

    def start(self, wait_ready: bool = True) -> "FleetServer":
        with self._lock:    # _stopped is read under the lock by the
            #                 dispatch park path; write it there too
            self._stopped = False
        self.sup.start(wait_ready=wait_ready)
        return self

    def stop(self, timeout: float = 30.0) -> bool:
        """Drain-stop the fleet; True iff every worker exited cleanly.
        Anything still unanswered afterwards fails with a structured
        ``Rejected("shutdown")`` — nobody hangs on a dead fleet."""
        with self._lock:
            # under the lock: _dispatch's park path checks this flag
            # under the same lock, so a request either parks before the
            # sweep below (and is swept) or fails at the park site
            self._stopped = True
        clean = self.sup.stop(timeout=timeout)
        with self._lock:
            leftovers = [r for r in (list(self._records.values())
                                     + self._parked) if not r.warmup]
            self._records.clear()
            self._parked.clear()
        for rec in leftovers:
            _end_wire(rec, outcome="shutdown")
            if rec.probe:
                rec.fut.set_exception(Rejected(
                    "shutdown", "fleet stopping",
                    content_hash=rec.key))
                continue
            self.flight.fail(rec.key, Rejected(
                "shutdown", "fleet stopping", content_hash=rec.key))
            self._count("rejected_shutdown")
        return clean

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving ------------------------------------------------------- #

    def submit(self, req: SolveRequest, tenant: str = "default",
               timeout: Optional[float] = None) -> Future:
        """Admit one request; the future resolves to a ``SolveResult``
        or fails with a structured ``Rejected`` (never raises)."""
        t0 = time.monotonic()
        timeout = self.default_timeout if timeout is None else timeout
        try:
            req.validate()
        except Rejected as e:
            self._count("rejected_invalid")
            return _failed(e)
        key = req.content_hash()

        # Tracing: the fleet-level root span — every dispatch/replay
        # wire span and (cross-process) every worker-side span in this
        # request's causal tree descends from it.
        span = tracing.NULL_SPAN
        if tracing.enabled():
            span = tracing.begin(
                "fleet.request", kind="request", content_hash=key,
                signature=str(req.signature()), tenant=tenant)

        hit = self.cache.get(key)
        if hit is not None:
            # Served no matter what state the fleet is in: quota,
            # capacity and the breaker all gate COMPUTE, not answers
            # the fleet already holds.
            self._count("cache_hit")
            self._latency(t0)
            span.end(outcome="cache_hit")
            fut = Future()
            fut.set_result(dataclasses.replace(
                hit, cache_hit=True, coalesced=False))
            return fut

        if self._stopped:
            # a stopped fleet must answer, not park a request no
            # worker will ever pick up (cache hits above still serve —
            # answers the router holds cost nothing)
            self._count("rejected_shutdown")
            span.end(outcome="rejected_shutdown")
            return _failed(Rejected("shutdown", "fleet is stopped"))

        fut, leader = self.flight.claim(key)
        if span is not tracing.NULL_SPAN:
            if not leader:
                span.set(coalesced=True)
            fut.add_done_callback(
                lambda f: span.end(outcome=_outcome_of(f)))
        if not leader:
            self._count("coalesced")
            out = coalesced_future(fut)
            out.add_done_callback(lambda _f: self._latency(t0))
            return out

        rej = self._admit(tenant, key)
        if rej is not None:
            self.flight.fail(key, rej)
            fut.add_done_callback(lambda _f: self._latency(t0))
            return fut

        rec = _Inflight(
            key=key, sig=str(req.signature()), tenant=tenant,
            req_dict=req.spec(), t0=t0,
            deadline=None if timeout is None else t0 + timeout,
            span=span)
        fut.add_done_callback(lambda _f: self._release(tenant, t0))
        self._dispatch(rec)
        return fut

    def solve(self, req: SolveRequest, tenant: str = "default",
              timeout: Optional[float] = None) -> SolveResult:
        """Synchronous convenience: submit + wait. Raises ``Rejected``."""
        wait = self.default_timeout if timeout is None else timeout
        return self.submit(req, tenant=tenant, timeout=timeout).result(
            None if wait is None else wait + 60)

    def probe(self, slot: int, req: SolveRequest,
              timeout: Optional[float] = None) -> Future:
        """Targeted dispatch to ONE worker — the control plane's
        parity/latency probe (docs/CONTROL.md). Bypasses the shared
        cache, single-flight, quotas and the breaker on purpose: a
        probe exists to measure THAT worker's answer and latency, and
        a cache hit or a coalesce onto another worker's launch would
        measure nothing. The future resolves to the worker's own
        ``SolveResult`` or fails with a structured ``Rejected``; a
        probe is never replayed to a survivor (an answer from a
        different worker proves nothing about the probed one) and
        never enters the hot-signature warmup set."""
        t0 = time.monotonic()
        timeout = self.default_timeout if timeout is None else timeout
        fut: Future = Future()
        try:
            req.validate()
        except Rejected as e:
            fut.set_exception(e)
            return fut
        rec = _Inflight(
            key=req.content_hash(), sig=str(req.signature()),
            tenant="_control", req_dict=req.spec(), t0=t0,
            deadline=None if timeout is None else t0 + timeout,
            slot=slot, probe=True, fut=fut)
        self._dispatch(rec)
        return fut

    # -- elastic capacity (heat2d_tpu/autoscale/) ----------------------- #

    def add_worker(self) -> int:
        """Scale-up actuation: grow the pool by one worker. The new
        worker rejoins through the warm-restart machinery — its
        ``via="scale_up"`` ready event warm-gates it
        (``_on_worker_ready``), so until its hot-signature compiles
        land it is unroutable and scale-up can never put client
        traffic on an uncompiled worker."""
        return self.sup.add_worker()

    def retire_worker(self, slot: int, timeout: float = 30.0) -> bool:
        """Scale-down actuation: drain-to-retire one worker. The
        supervisor fences the routing table first
        (``_on_worker_retiring``), then drains; see
        ``Supervisor.retire_worker`` for the ordering contract.
        Returns True iff the drain was clean."""
        return self.sup.retire_worker(slot, timeout=timeout)

    # -- admission ----------------------------------------------------- #

    def _policy(self, tenant: str) -> TenantPolicy:
        return self.quotas.get(tenant, self.default_policy)

    def _admit(self, tenant: str, key: str) -> Optional[Rejected]:
        """Reserve capacity for a fresh leader, or explain why not."""
        pol = self._policy(tenant)
        with self._lock:
            shed = self._shed_watermark
            watermark = int(math.ceil(
                (HIGH_WATERMARK if shed is None else shed)
                * self.max_inflight))
            mine = self._tenant_inflight.get(tenant, 0)
            if mine >= pol.max_inflight:
                if self.registry is not None:
                    self.registry.counter("fleet_quota_rejected_total",
                                          tenant=tenant)
                self._count("rejected_quota")
                return Rejected(
                    "quota", f"tenant {tenant!r} at its in-flight "
                    f"limit {pol.max_inflight}", tenant=tenant,
                    content_hash=key)
            cap = (self.max_inflight if pol.priority == 0
                   else watermark)
            if self._total_inflight >= cap:
                self._count("rejected_overloaded")
                return Rejected(
                    "overloaded",
                    f"fleet at capacity ({self._total_inflight}/"
                    f"{self.max_inflight}"
                    + ("" if pol.priority == 0
                       else f"; standard-priority watermark "
                            f"{watermark}"
                            + (" (pre-emptive shed)"
                               if shed is not None else "")) + ")",
                    tenant=tenant, content_hash=key,
                    preemptive_shed=shed is not None)
            if not self.breaker.allow():
                self._count("rejected_degraded")
                return Rejected(
                    "degraded", "fleet is in degraded mode after "
                    "repeated worker failures: uncached load is shed "
                    "while workers recover", content_hash=key,
                    breaker_state=self.breaker.state)
            self._tenant_inflight[tenant] = mine + 1
            self._total_inflight += 1
            self._gauge_inflight_locked()
        return None

    def set_preemptive_shed(self, watermark: Optional[float]) -> None:
        """Control-plane actuator (docs/CONTROL.md): temporarily lower
        the standard-priority admission watermark below
        ``HIGH_WATERMARK`` — sustained SLO burn sheds low-priority
        tenants BEFORE the breaker trips. ``None`` restores the
        default. Priority-0 tenants, cache hits and coalesced
        followers are untouched: they never consult the watermark."""
        if watermark is not None and not (0 <= watermark <= 1):
            raise ValueError(
                f"watermark must be in [0, 1], got {watermark}")
        with self._lock:
            self._shed_watermark = watermark
        if self.registry is not None:
            self.registry.gauge(
                "fleet_shed_watermark",
                HIGH_WATERMARK if watermark is None else watermark)

    def _release(self, tenant: str, t0: float) -> None:
        with self._lock:
            self._tenant_inflight[tenant] = max(
                0, self._tenant_inflight.get(tenant, 0) - 1)
            self._total_inflight = max(0, self._total_inflight - 1)
            self._gauge_inflight_locked()
        self._latency(t0)

    # -- dispatch / failover ------------------------------------------- #

    def _routable(self) -> List[int]:
        """Alive slots minus the still-warming and the retiring ones —
        unless ALL alive slots are cold (full-fleet restart): then a
        cold worker beats parking. A retiring slot never routes, even
        under that fallback: its drain is already under way."""
        slots = self.sup.alive_slots()
        with self._lock:
            alive = [s for s in slots if s not in self._retiring]
            warm = [s for s in alive if s not in self._cold]
        return warm or alive

    def _dispatch(self, rec: _Inflight) -> None:
        """Route ``rec`` to an alive worker, parking when none exist.
        A fresh wire id per dispatch: a late answer from a fenced
        worker can never alias a replay's."""
        tried = set()
        while True:
            with self._lock:
                retiring = set(self._retiring)
            alive = set(self.sup.alive_slots()) - retiring
            pool = ([rec.slot] if rec.warmup or rec.probe
                    else [s for s in self._routable()
                          if s not in tried])
            pool = [s for s in pool if s in alive]
            if not pool:
                if rec.warmup:
                    return      # its worker died; nothing to warm
                if rec.probe:
                    # a probe never parks or retargets: its whole point
                    # is THAT worker, and that worker is gone
                    rec.fut.set_exception(Rejected(
                        "worker_lost",
                        f"probe target slot {rec.slot} is not alive",
                        content_hash=rec.key))
                    return
                with self._lock:
                    stopped = self._stopped
                    if not stopped:
                        self._parked.append(rec)
                        if self.registry is not None:
                            self.registry.gauge("fleet_parked",
                                                len(self._parked))
                if stopped:
                    # stop()'s sweep may already have run: parking now
                    # would strand the caller's future forever
                    self.flight.fail(rec.key, Rejected(
                        "shutdown", "fleet stopping",
                        content_hash=rec.key))
                    self._count("rejected_shutdown")
                    return
                log.info("no alive workers: parked request %s…",
                         rec.key[:12])
                return
            slot = route_signature(rec.sig, pool)
            with self._lock:
                self._next_rid += 1
                rid = self._next_rid
                rec.rid, rec.slot = rid, slot
                self._records[rid] = rec
                if rec.warmup:
                    self._warming.setdefault(slot, set()).add(rid)
                elif not rec.probe:
                    # hot-signature set: recency-ordered, bounded
                    # (probes are control traffic, not client demand —
                    # they must not shape the warmup set)
                    self._hot.pop(rec.sig, None)
                    self._hot[rec.sig] = rec.req_dict
                    while len(self._hot) > MAX_HOT_SIGNATURES:
                        self._hot.pop(next(iter(self._hot)))
            msg = {"id": rid, "req": rec.req_dict}
            if rec.warmup:
                msg["event"] = "warmup"
            elif getattr(rec.span, "ctx", None) is not None:
                # one wire span per HOP: begun at send, closed by the
                # response / death / deadline — its context rides the
                # DISPATCH line so the worker's spans nest under it
                rec.wire_span = tracing.begin(
                    "fleet.dispatch", kind="wire", parent=rec.span.ctx,
                    slot=slot, rid=rid, replay=rec.replays)
                msg["trace"] = rec.wire_span.ctx.to_wire()
            try:
                self.sup.send(slot, msg)
                return
            except WorkerGone:
                _end_wire(rec, outcome="worker_gone_at_send")
                with self._lock:
                    owned = self._records.pop(rid, None) is not None
                    if rec.warmup:
                        self._warming.get(slot, set()).discard(rid)
                if rec.warmup:
                    return
                if rec.probe:
                    if owned:
                        rec.fut.set_exception(Rejected(
                            "worker_lost",
                            f"probe target slot {rec.slot} died at "
                            f"send", content_hash=rec.key))
                    return
                if not owned:
                    # a concurrent _on_worker_lost sweep already popped
                    # this rid and owns the replay — retrying here
                    # would double-dispatch the request
                    return
                tried.add(slot)

    def _on_response(self, slot: int, msg: dict) -> None:
        with self._lock:
            rec = self._records.pop(msg.get("id"), None)
        if rec is None:
            return      # late line from a fenced worker, or a replayed
            #             request already answered — dropped by design:
            #             no record, no span — a fenced worker's lines
            #             can never attach spans to a replay's trace
        if rec.warmup:
            self._warmup_done(rec)
            return
        if rec.probe:
            # a probe's answer goes straight to its caller: no cache
            # write, no single-flight, no per-signature SLO counters —
            # control traffic must not dress up as client outcomes
            if msg.get("ok"):
                try:
                    rec.fut.set_result(wire.decode_result(msg))
                except (KeyError, ValueError) as e:
                    rec.fut.set_exception(Rejected(
                        "error", f"undecodable probe response: {e!r}",
                        content_hash=rec.key))
            else:
                rec.fut.set_exception(wire.decode_rejection(msg))
            return
        _end_wire(rec, outcome="ok" if msg.get("ok") else "rejected")
        if msg.get("ok"):
            try:
                res = wire.decode_result(msg)
            except (KeyError, ValueError) as e:
                self.flight.fail(rec.key, Rejected(
                    "error", f"undecodable worker response: {e!r}",
                    content_hash=rec.key))
                self._count("error")
                return
            self.cache.put(rec.key, res)
            self.flight.resolve(rec.key, res)
            self.breaker.record_success()
            self._count("completed")
            if self.registry is not None:
                # per-signature latency/outcome: obs/slo.py's sources
                self.registry.observe(
                    "fleet_signature_latency_s",
                    time.monotonic() - rec.t0, signature=rec.sig)
                self.registry.counter(
                    "fleet_signature_requests_total",
                    signature=rec.sig, outcome="completed")
        else:
            # A structured worker-side rejection is an ANSWER (queue
            # full, watchdog timeout...), not a fleet fault: it must
            # not feed the breaker.
            exc = wire.decode_rejection(msg)
            self.flight.fail(rec.key, exc)
            self._count("rejected_" + exc.code)
            if self.registry is not None:
                self.registry.counter(
                    "fleet_signature_requests_total",
                    signature=rec.sig, outcome="rejected_" + exc.code)

    def _on_worker_lost(self, slot: int) -> None:
        with self._lock:
            lost = [r for r in self._records.values()
                    if r.slot == slot]
            for r in lost:
                self._records.pop(r.rid, None)
            # a dying warmup is moot — the replacement re-warms
            self._warming.pop(slot, None)
            self._cold.discard(slot)
            lost = [r for r in lost if not r.warmup]
        self.breaker.record_failure()
        probes = [r for r in lost if r.probe]
        lost = [r for r in lost if not r.probe]
        for rec in probes:
            # never replayed: an answer from a survivor would prove
            # nothing about the worker the probe was aimed at
            rec.fut.set_exception(Rejected(
                "worker_lost", "probed worker died mid-probe",
                content_hash=rec.key))
        if not lost:
            return
        log.warning("worker %d died with %d request(s) in flight; "
                    "replaying to survivors", slot, len(lost))
        for rec in lost:
            rec.replays += 1
            self.replays += 1
            _end_wire(rec, outcome="worker_lost")
            if self.registry is not None:
                self.registry.counter("fleet_failover_replays_total")
            if rec.replays > self.max_replays:
                self.flight.fail(rec.key, Rejected(
                    "worker_lost",
                    f"request lost {rec.replays} workers (limit "
                    f"{self.max_replays} replays)",
                    content_hash=rec.key))
                self._count("rejected_worker_lost")
            else:
                if getattr(rec.span, "ctx", None) is not None:
                    # the failover decision as an instant marker in the
                    # request's trace — the "replay" critical-path
                    # segment is the gap this event sits in
                    tracing.event("fleet.replay", parent=rec.span.ctx,
                                  from_slot=slot, replay=rec.replays)
                self._dispatch(rec)

    def _on_worker_retiring(self, slot: int) -> None:
        """The retire fence — fired by the supervisor BEFORE the drain
        begins (the satellite ordering fix): the slot leaves the
        routing set here, so no request admitted mid-retire can be
        routed onto the draining worker. In-flight records for the
        slot deliberately stay: a clean drain flushes their answers;
        an unclean one ends in ``_on_worker_lost``, which replays
        them."""
        with self._lock:
            self._retiring.add(slot)
            self._warming.pop(slot, None)
            self._cold.discard(slot)
        log.info("worker %d fenced out of routing for retirement",
                 slot)

    def _on_worker_ready(self, slot: int, restarted: bool = False,
                         via: Optional[str] = None) -> None:
        if restarted or via == "scale_up":
            # Replacements AND scale-up spawns warm-gate: both join a
            # fleet with live traffic and a hot-signature set, so they
            # stay unroutable until their compiles land. Only the
            # fleet-start first spawns skip the gate — they have no
            # hot set worth waiting for, and gating them would race
            # the first client dispatches.
            self._begin_warmup(slot)
        self._flush_parked()

    def _begin_warmup(self, slot: int) -> None:
        """Two-phase rejoin: replay the hot-signature set to the fresh
        worker (compile warmup, off the client path) and keep the slot
        out of routing until the last warmup answer lands."""
        now = time.monotonic()
        with self._lock:
            hot = list(self._hot.items())
        if not hot:
            return              # nothing to warm (fleet start)
        with self._lock:
            self._cold.add(slot)
            # the -1 sentinel holds the set non-empty until every
            # warmup dispatch below has registered (else an early
            # answer could mark the slot warm mid-enqueue)
            self._warming[slot] = {-1}
        if self.registry is not None:
            self.registry.counter("fleet_worker_warmups_total")
        log.info("worker %d warming %d hot signature(s) before "
                 "rejoining the routing set", slot, len(hot))
        for sig, spec in hot:
            # one warmup per signature: the WORKER walks the padded-
            # capacity ladder itself (fleet/worker._warm_signature)
            self._dispatch(_Inflight(
                key=None, sig=sig, tenant="_warmup",
                req_dict=dict(spec), t0=now,
                deadline=now + (self.default_timeout or 60.0),
                slot=slot, warmup=True))
        done = _Inflight(key=None, sig="", tenant="_warmup",
                         req_dict={}, t0=now, deadline=None,
                         slot=slot, rid=-1, warmup=True)
        self._warmup_done(done)     # release the enqueue sentinel

    def _warmup_done(self, rec: _Inflight) -> None:
        with self._lock:
            pend = self._warming.get(rec.slot)
            if pend is not None:
                pend.discard(rec.rid)
                if pend:
                    return
                self._warming.pop(rec.slot, None)
            self._cold.discard(rec.slot)
        log.info("worker %d warm — rejoining the routing set",
                 rec.slot)

    def _flush_parked(self) -> None:
        with self._lock:
            parked, self._parked = self._parked, []
            if self.registry is not None:
                self.registry.gauge("fleet_parked", 0)
        for rec in parked:
            self._dispatch(rec)

    def _expire_overdue(self) -> None:
        """Monitor-tick sweep: fleet-level deadlines bound parked AND
        in-flight requests, whatever state the workers are in."""
        now = time.monotonic()
        overdue = []
        with self._lock:
            for rid in [rid for rid, r in self._records.items()
                        if r.deadline is not None
                        and r.deadline <= now]:
                overdue.append(self._records.pop(rid))
            keep = []
            for r in self._parked:
                (overdue if r.deadline is not None
                 and r.deadline <= now else keep).append(r)
            self._parked = keep
        for rec in overdue:
            if rec.warmup:
                # an overdue warmup must not wedge the slot cold
                self._warmup_done(rec)
                continue
            if rec.probe:
                rec.fut.set_exception(Rejected(
                    "timeout", "probe exceeded its deadline",
                    content_hash=rec.key,
                    waited_s=round(now - rec.t0, 6)))
                continue
            _end_wire(rec, outcome="timeout")
            self.flight.fail(rec.key, Rejected(
                "timeout", "request exceeded its fleet deadline",
                content_hash=rec.key,
                waited_s=round(now - rec.t0, 6)))
            self._count("rejected_timeout")
        # Parked work re-dispatches on any tick with a live worker —
        # belt-and-braces for the park-vs-ready race where a request
        # parks just after the ready flush swept the list.
        if self._parked and self.sup.alive_slots():
            self._flush_parked()

    # -- metrics ------------------------------------------------------- #

    def _count(self, outcome: str) -> None:
        if self.registry is not None:
            self.registry.counter("fleet_requests_total",
                                  outcome=outcome)

    def _latency(self, t0: float) -> None:
        if self.registry is not None:
            self.registry.observe("fleet_e2e_latency_s",
                                  time.monotonic() - t0)

    def _gauge_inflight_locked(self) -> None:
        if self.registry is not None:
            self.registry.gauge("fleet_inflight", self._total_inflight)
