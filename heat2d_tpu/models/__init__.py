from heat2d_tpu.models.solver import Heat2DSolver, RunResult

__all__ = ["Heat2DSolver", "RunResult"]
