# Lazy re-exports: parallel.sharded imports heat2d_tpu.models.engine, and an
# eager solver import here would close an import cycle (solver -> sharded ->
# models package -> solver).
__all__ = ["Heat2DSolver", "RunResult"]


def __getattr__(name):
    if name in __all__:
        from heat2d_tpu.models import solver

        return getattr(solver, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
