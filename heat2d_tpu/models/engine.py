"""Time-stepping loop assembly — the framework's engine.

The reference's four ``main()`` step loops (SURVEY.md §3) collapse into two
compiled loop shapes, generic over a per-step function:

- fixed-step: ``lax.fori_loop`` over STEPS (the default; the reference's
  effective behavior since its convergence predicate is dead code —
  SURVEY.md A.2);
- convergence: a ``lax.while_loop`` that runs INTERVAL-step chunks and
  early-exits when the global residual Σ(Δu)² drops below SENSITIVITY —
  the *intended* behavior of grad1612_mpi_heat.c:262-271, implemented
  correctly here (the reference tests a stale loop variable and never
  fires).

Both keep everything on-device: the double buffer is a functional loop
carry (no ``iz = 1-iz`` plane selector — SURVEY.md C5), and the residual
never syncs to the host mid-run (the reference syncs implicitly via
MPI_Allreduce; here the psum/sum stays in the carry).

``step_fn`` is any ``u -> u`` (single-device golden model, Pallas kernel,
a shard-local step with ppermute halo exchange inside ``shard_map``, or —
since the implicit routes landed — a Crank-Nicolson ADI sweep
(``ops/tridiag.adi_step``) or a multigrid-solved CN step
(``ops/multigrid.mg_step``): the loops are scheme-agnostic, which is
exactly how ``config.method`` composes without a second engine — the
solver's implicit runner feeds these same loops, with the per-INTERVAL
residual pair meaning the same thing at any step size);
``residual_fn`` is ``(u_new, u_old) -> scalar`` and performs its own psum
when running sharded.

In-loop telemetry: every convergence loop takes an optional ``tap`` — a
host callback ``tap(steps_done, residual)`` invoked at each residual
check via ``jax.debug.callback`` (fire-and-forget: the carry never syncs
to the host). ``tap=None`` (the default) adds ZERO equations to the
traced program, so the timed hot path is byte-identical with telemetry
disabled — obs/stream.TelemetryStream is the standard collector.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _emit(tap: Optional[Callable], k, res) -> None:
    """Stream one (steps_done, residual) pair to the host collector.
    Python-level guard: no tap, no trace-time footprint at all. Call
    sites must pass values that already exist in the trace (or guard
    derived arguments themselves) — evaluating a fresh ``k + n`` in the
    argument list would leave a dead equation in the tapless jaxpr and
    break the byte-identical-hot-path contract the tests pin."""
    if tap is not None:
        jax.debug.callback(tap, k, res, ordered=False)


def run_fixed(step_fn: Callable, u0, steps: int):
    """Run exactly ``steps`` steps. Returns (u_final, steps_done)."""
    u = lax.fori_loop(0, steps, lambda _, u: step_fn(u), u0)
    return u, jnp.asarray(steps, jnp.int32)


def run_fixed_stacked(step_fn: Callable, u0, steps: int):
    """Run exactly ``steps`` steps, additionally returning the state
    BEFORE each step stacked on a leading axis: ``states[t]`` is the
    input of step ``t`` (``states[0] == u0``), so a reverse sweep can
    linearize every step at its true evaluation point. This is the
    trajectory store of the full-storage adjoint and the per-segment
    recompute of the checkpointed adjoint (heat2d_tpu/diff) — O(steps)
    memory, which is exactly the cost the checkpointed schedule
    amortizes to O(steps/K + K). Returns (u_final, states)."""
    def body(u, _):
        return step_fn(u), u

    u, states = lax.scan(body, u0, None, length=steps)
    return u, states


def run_convergence(step_fn: Callable, residual_fn: Callable, u0,
                    steps: int, interval: int, sensitivity: float,
                    tap: Optional[Callable] = None):
    """Run up to ``steps`` steps, checking the global residual every
    ``interval`` steps and stopping early once it falls below
    ``sensitivity``. Returns (u_final, steps_done).

    The residual compares the last two planes of a chunk — the same
    quantity grad1612_mpi_heat.c:264-267 accumulates (Σ over cells of
    (u_new - u_old)²) before its MPI_Allreduce.
    """
    interval = min(interval, steps) if steps else interval

    def chunk_body(carry):
        u_prev, u, k, _ = carry
        n = jnp.minimum(interval, steps - k)

        def body(_, pu):
            p, c = pu
            del p
            return (c, step_fn(c))

        u_prev, u = lax.fori_loop(0, n, body, (u_prev, u))
        res = residual_fn(u, u_prev).astype(jnp.float32)
        k = k + n
        _emit(tap, k, res)
        return (u_prev, u, k, res)

    def cond(carry):
        _, _, k, res = carry
        return jnp.logical_and(k < steps, res >= sensitivity)

    init = (u0, u0, jnp.asarray(0, jnp.int32),
            jnp.asarray(jnp.inf, jnp.float32))
    _, u, k, _ = lax.while_loop(cond, chunk_body, init)
    return u, k


def run_convergence_fused(chunk_resid_fn, multi_step_fn, u0,
                          steps: int, interval: int, sensitivity: float,
                          tap: Optional[Callable] = None):
    """run_convergence_chunked for engines whose multi-step primitive can
    emit the residual itself: ``chunk_resid_fn(u, n) -> (u, residual)``
    advances n steps and returns Σ(Δu)² of the final plane pair — the
    same pair the chunked loop forms from its ``interval-1`` fused steps
    plus one tracked step, without the tracked step or the separate
    full-grid reduction (the C2R/D2R window sweeps fuse both into the
    chunk's last band sweep). Schedule and early-exit semantics
    are identical to run_convergence_chunked; only the residual's
    summation order differs (per-band partials), an f32-ulp deviation of
    the same class as the FMA step form such engines already use.

    Telemetry note: the trailing ``steps % interval`` remainder runs
    UNCHECKED (no residual is computed for it — the intended reference
    schedule), so a tap streams one point per full INTERVAL chunk only;
    on non-converging runs the trajectory ends ``steps % interval``
    short of steps_done. The unfused run_convergence checks (and
    streams) its final partial chunk, so trajectory shapes differ
    between engine routes for non-multiple step budgets."""
    if steps:
        interval = max(1, min(interval, steps))
    n_chunks = steps // interval if interval else 0
    remainder = steps - n_chunks * interval

    def body(carry):
        u, c, _ = carry
        u, res = chunk_resid_fn(u, interval)
        res = res.astype(jnp.float32)
        c = c + 1
        if tap is not None:   # guard: c * interval is telemetry-only
            _emit(tap, c * interval, res)
        return (u, c, res)

    def cond(carry):
        _, c, res = carry
        return jnp.logical_and(c < n_chunks, res >= sensitivity)

    init = (u0, jnp.asarray(0, jnp.int32),
            jnp.asarray(jnp.inf, jnp.float32))
    u, c, res = lax.while_loop(cond, body, init)
    k = (c * interval).astype(jnp.int32)
    if remainder:
        converged = res < sensitivity
        u = lax.cond(converged, lambda v: v,
                     lambda v: multi_step_fn(v, remainder), u)
        k = jnp.where(converged, k, k + remainder).astype(jnp.int32)
    return u, k


def run_convergence_chunked(multi_step_fn, step_fn, residual_fn, u0,
                            steps: int, interval: int, sensitivity: float,
                            tap: Optional[Callable] = None):
    """Convergence loop for engines with an efficient *static* multi-step
    primitive (e.g. the VMEM-resident Pallas kernel, where N steps run in
    one kernel invocation): each full INTERVAL chunk is ``interval-1``
    fused steps plus one tracked step for the residual pair — expressed
    as ``run_convergence_fused`` with that pair assembled here. A
    trailing ``steps % interval`` remainder runs unchecked (the intended
    reference schedule checks only every INTERVAL steps). Returns
    (u, steps_done).
    """
    def chunk_resid(u, n):
        u_prev = multi_step_fn(u, n - 1)
        u_new = step_fn(u_prev)
        return u_new, residual_fn(u_new, u_prev)

    return run_convergence_fused(chunk_resid, multi_step_fn, u0,
                                 steps, interval, sensitivity, tap=tap)
