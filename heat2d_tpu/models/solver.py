"""Heat2DSolver — one engine, pluggable execution modes (SURVEY.md §7.1).

The reference ships four standalone programs; this facade reproduces each as
a mode of a single solver:

====================  ====================================================
mode                  reference counterpart
====================  ====================================================
serial                1-task runs of the MPI programs (Report.pdf 1/1 col)
pallas                grad1612_cuda_heat.cu single-accelerator kernel
dist1d                mpi_heat2Dn.c row-strip decomposition
dist2d                grad1612_mpi_heat.c 2D block decomposition
hybrid                grad1612_hybrid_heat.c (multi-chip mesh × per-chip
                      tiled kernel; the OpenMP tier maps to intra-chip
                      parallelism, which the compiler owns)
====================  ====================================================

Unlike the reference's CUDA program (SURVEY.md A.1: first step reads a
zeroed source plane and the result never leaves the device), every mode
here steps from the real initial condition and returns the final grid.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from heat2d_tpu.config import ConfigError, HeatConfig
from heat2d_tpu.models import engine
from heat2d_tpu.ops.init import inidat
from heat2d_tpu.ops.stencil import residual_sq, stencil_step

log = logging.getLogger("heat2d_tpu.solver")
from heat2d_tpu.parallel.mesh import make_mesh
from heat2d_tpu.parallel.sharded import make_sharded_runner, sharded_inidat
from heat2d_tpu.utils.timing import timed_call


@dataclasses.dataclass
class RunResult:
    u: np.ndarray           # final global grid, host-side, row-major
    steps_done: int
    elapsed: float          # seconds, reference timing protocol
    config: HeatConfig
    # Compile+warmup wall-clock of the priming run — the setup cost the
    # timed span excludes (utils/timing.TimedCall); None when untimed or
    # the warmup was skipped (repeat calls of a warm runner).
    warmup_s: Optional[float] = None

    @property
    def mcells_per_s(self) -> float:
        """Cell-updates/s in millions — BASELINE.md's derived metric
        (cells × iterations / time)."""
        if self.elapsed <= 0 or self.steps_done == 0:
            return float("nan")
        nx, ny = self.config.shape
        return nx * ny * self.steps_done / self.elapsed / 1e6

    def to_record(self) -> dict:
        """Structured run record — the unified schema (obs/record.py,
        SURVEY.md §5.5): payload keys unchanged, plus the shared envelope
        (schema tag, timestamp, device, world) and the compile/warmup
        metric."""
        from heat2d_tpu.obs.record import build_record
        return build_record(
            "run", config=self.config, steps_done=self.steps_done,
            elapsed_s=self.elapsed, mcells_per_s=self.mcells_per_s,
            warmup_s=self.warmup_s)


class Heat2DSolver:
    def __init__(self, config: HeatConfig, devices=None, telemetry=None):
        """``telemetry``: optional obs.stream.TelemetryStream — wires the
        convergence loops' residual tap into the compiled program (an
        extra debug_callback per INTERVAL chunk). None (default) leaves
        the traced program byte-identical to the untelemetered one, so
        the timed hot path pays zero cost."""
        self.config = config
        self.telemetry = telemetry
        if (config.accum_dtype == "float64"
                and not jax.config.jax_enable_x64):
            # Without x64, astype(float64) silently truncates to f32 and
            # the C-double-promotion parity mode would be a no-op.
            raise ConfigError(
                "accum_dtype='float64' requires jax_enable_x64; call "
                "jax.config.update('jax_enable_x64', True) first (the CLI "
                "does this automatically)")
        self.mesh = None
        self._sharding = None
        if config.mode == "dist1d":
            nw = config.numworkers or config.gridx
            self.mesh = make_mesh(nw, 1, devices=devices)
        elif config.mode in ("dist2d", "hybrid"):
            self.mesh = make_mesh(config.gridx, config.gridy, devices=devices)
        self._runner = None

    # ------------------------------------------------------------------ #

    def init_state(self):
        """Initial condition, placed where the run needs it (sharded for
        distributed modes)."""
        cfg = self.config
        if self.mesh is not None:
            return sharded_inidat(cfg, self.mesh)
        return inidat(cfg.nxprob, cfg.nyprob)

    def place(self, u):
        """Device-put a host grid with this solver's sharding (the
        device_put-with-NamedSharding analogue of the reference's work
        distribution, mpi_heat2Dn.c:107-112). Pads to equal shards when
        the mesh does not divide the grid (the averow/extra analogue)."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from heat2d_tpu.parallel.sharded import padded_global_shape
            pnx, pny = padded_global_shape(self.config, self.mesh)
            u = np.asarray(u)
            if (pnx, pny) != u.shape:
                u = np.pad(u, ((0, pnx - u.shape[0]), (0, pny - u.shape[1])))
            ax, ay = self.mesh.axis_names
            return jax.device_put(u, NamedSharding(self.mesh, P(ax, ay)))
        return jax.device_put(u)

    def _chunk_kernel(self):
        if self.config.mode == "hybrid":
            try:
                from heat2d_tpu.ops.pallas_stencil import (
                    make_shard_chunk_kernel)
            except ImportError as e:
                raise ConfigError(
                    f"mode {self.config.mode!r} needs the Pallas kernel, "
                    f"which failed to import: {e}") from e
            return make_shard_chunk_kernel(self.config)
        return None

    def make_runner(self):
        """Compiled ``u0 -> (u_final, steps_done)``."""
        if self._runner is not None:
            return self._runner
        cfg = self.config
        tap = self.telemetry.tap if self.telemetry is not None else None
        log.debug("building runner: mode=%s %dx%d steps=%d "
                  "convergence=%s telemetry=%s", cfg.mode, cfg.nxprob,
                  cfg.nyprob, cfg.steps, cfg.convergence,
                  tap is not None)
        if self.mesh is not None:
            self._runner, self._sharding = make_sharded_runner(
                cfg, self.mesh, chunk_kernel=self._chunk_kernel(),
                tap=tap)
            return self._runner

        accum = jnp.dtype(cfg.accum_dtype)
        if cfg.method != "explicit":
            self._runner = self._make_implicit_runner(accum, tap)
            return self._runner
        if cfg.mode == "pallas":
            try:
                from heat2d_tpu.ops.pallas_stencil import (
                    make_single_chip_runner)
            except ImportError as e:
                raise ConfigError(
                    f"mode 'pallas' needs the Pallas kernel, which failed "
                    f"to import: {e}") from e
            self._runner = make_single_chip_runner(cfg, tap=tap)
            return self._runner

        if cfg.problem != "heat5":
            # Registry families (config validated: serial + explicit
            # only): the step comes from the family's jnp reference
            # kernel; the engine loops are family-agnostic. The heat5
            # branch below is the pre-registry closure, byte-for-byte
            # (jaxpr-pinned).
            from heat2d_tpu.problems import get_family
            fam = get_family(cfg.problem)

            def step(u):
                return fam.step(u, cfg.cx, cfg.cy)
        else:
            def step(u):
                return stencil_step(u, cfg.cx, cfg.cy, accum)

        def multi(u, n):
            from jax import lax
            return lax.fori_loop(0, n, lambda _, v: step(v), u,
                                 unroll=False)

        def run(u):
            if cfg.convergence:
                # Chunked loop (same plane sequence and steps_done as
                # run_convergence — the tests pin dist modes, which use
                # it, bitwise to serial): carrying the residual pair
                # only at each INTERVAL boundary instead of every step
                # measured ~2x faster at 2560x2048+ (the per-step
                # (prev, cur) carry doubled the serial conv cost,
                # sweep_conv.md round 4).
                return engine.run_convergence_chunked(
                    multi, step, lambda a, b: residual_sq(a, b, accum),
                    u, cfg.steps, cfg.interval, cfg.sensitivity, tap=tap)
            return engine.run_fixed(step, u, cfg.steps)

        self._runner = jax.jit(run)
        return self._runner

    def _make_implicit_runner(self, accum, tap):
        """Compiled runner for the implicit schemes (config.method
        "adi"/"mg"): the SAME engine loops drive a Crank-Nicolson
        step instead of the explicit stencil — fixed-step through one
        fused multi-step, convergence through the chunked loop with
        the usual residual pair. Unconditionally stable: (cx, cy) are
        dt-scaled diffusion numbers chosen by accuracy, not by the
        kx+ky <= 1/2 box (ops/stability.py; config validated this).
        mode="pallas" + method="adi" engages kernel TD
        (ops/tridiag.py) on viable shapes; everything else runs the
        scan/jnp route."""
        cfg = self.config
        from heat2d_tpu.ops import multigrid as mgrid
        from heat2d_tpu.ops import tridiag as td

        if cfg.method == "adi":
            use_kernel = (cfg.mode == "pallas"
                          and td.adi_kernel_viable(cfg.nxprob,
                                                   cfg.nyprob))
            if use_kernel:
                cxa = jnp.full((1,), cfg.cx, jnp.float32)
                cya = jnp.full((1,), cfg.cy, jnp.float32)

                def step(u):
                    return td.adi_sweep_kernel(u[None], cxa, cya)[0]

                def multi(u, n):
                    return td.batched_adi_kernel(u[None], cxa, cya,
                                                 steps=n)[0]
            else:
                def step(u):
                    return td.adi_step(u, cfg.cx, cfg.cy)

                def multi(u, n):
                    return td.adi_multi_step(u, n, cfg.cx, cfg.cy)
        else:
            def step(u):
                return mgrid.mg_step(u, cfg.cx, cfg.cy)

            def multi(u, n):
                return mgrid.mg_multi_step(u, n, cfg.cx, cfg.cy)

        def run(u):
            if cfg.convergence:
                return engine.run_convergence_chunked(
                    multi, step, lambda a, b: residual_sq(a, b, accum),
                    u, cfg.steps, cfg.interval, cfg.sensitivity,
                    tap=tap)
            u = multi(u, cfg.steps)
            return u, jnp.asarray(cfg.steps, jnp.int32)

        return jax.jit(run)

    def run(self, u0=None, timed: bool = True, warmup: bool = True,
            gather: bool = True) -> RunResult:
        """Init (unless given), step, gather. Timing follows the reference
        protocol: compile excluded (warmup), barrier-fenced, max over
        processes (SURVEY.md §5.1). Pass ``warmup=False`` on repeat calls
        of an already-executed runner to skip the untimed priming run.

        ``gather=False`` skips the cross-host allgather and padding crop:
        ``result.u`` stays the (possibly host-spanning, possibly padded)
        device array, for callers that write output per-shard
        (io.write_binary_sharded — the MPI-IO path) instead of
        materializing the global grid on every host.
        """
        if u0 is None:
            u0 = self.init_state()
        runner = self.make_runner()
        warmup_s = None
        if timed:
            tc = timed_call(runner, u0, warmup=warmup)
            (u, k), elapsed = tc
            warmup_s = tc.warmup_s
        else:
            u, k = jax.block_until_ready(runner(u0))
            elapsed = float("nan")
        if self.telemetry is not None:
            # Drain in-flight debug_callback work so the stream is
            # complete when the caller reads it right after run().
            from heat2d_tpu.obs.stream import flush_taps
            flush_taps()
        if gather:
            from heat2d_tpu.parallel.multihost import gather_to_host
            u = gather_to_host(u)
            if u.shape != self.config.shape:
                # Strip the equal-shard padding (uneven decomposition).
                u = u[:self.config.nxprob, :self.config.nyprob]
        log.info("run done: steps_done=%d elapsed_s=%.6g warmup_s=%s",
                 int(k), elapsed,
                 f"{warmup_s:.6g}" if warmup_s is not None else None)
        return RunResult(u=u, steps_done=int(k),
                         elapsed=elapsed, config=self.config,
                         warmup_s=warmup_s)
