"""Wall-clock-to-solution at matched accuracy — the headline harness.

Mcells/s measures how fast a kernel burns steps; it says nothing about
how fast a method reaches an ANSWER. This module measures the thing
the ROADMAP's algorithmic-speed item is actually about: the wall-clock
(and modeled) time for each time-stepping scheme to reach the same
physical time ``t_final`` at the same (or better) L2 accuracy against
the analytic separable-mode solution (``ops/analytic.py`` — the
semi-discrete reference, so the comparison isolates time-stepping
error; both schemes share the spatial operator exactly).

The contract (ISSUE 14 / the CI ``implicit-gate``): the explicit
scheme is pinned to the stability box (``ops/stability.py`` validates
it here — implicit legs skip the check by design), so its step count
scales as O(1/dx^2); the Crank-Nicolson ADI leg runs ``step_ratio``x
fewer steps at ``step_ratio``x the diffusion number — the SAME
``t_final`` — and must land at matched accuracy. The modeled speedup
uses a step-cost model in explicit-sweep units (an ADI step is ~10
sweep-equivalents: two tridiagonal sweeps, two half-RHS stencils and
the transposes), so the verdict is deterministic on any host while
the measured wall-clock rides beside it (``tpu_smoke.py`` records the
real-hardware numbers).

Emitted metric families (docs/ALGORITHMS.md): ``adi_time_to_solution_s``
/ ``adi_wall_speedup`` / ``mg_time_to_solution_s`` gauges when a
registry is given.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from heat2d_tpu.ops import analytic
from heat2d_tpu.ops.stability import check_explicit_stability

#: Step-cost model in explicit-sweep-equivalent units (the modeled
#: wall-clock's deterministic backbone): one explicit step streams the
#: grid once; an ADI step runs 2 tridiagonal sweeps (forward + back
#: substitution each) + 2 half-RHS stencils + transpose traffic; an
#: MG step runs MG_CYCLES V(2,2) cycles of smoothing sweeps (each a
#: stencil pass) plus the transfer hierarchy (~4/3 of the finest
#: level).
STEP_UNITS = {"explicit": 1.0, "adi": 10.0, "mg": 16.0}

#: Accuracy-match margin: the implicit leg's L2 error may exceed the
#: explicit leg's by at most this factor (the analytic expectation is
#: that it sits ORDERS below — O(dt^2) vs O(dt)), OR sit below the
#: dtype's roundoff floor: an ADI step at diffusion number c forms
#: intermediates ~c x the state that cancel back down, so its
#: per-step roundoff is ~c*eps while the explicit leg's is ~eps —
#: both are noise, not discretization error, and the floor keeps the
#: verdict about the algorithm (under x64 the floor is irrelevant:
#: truncation dominates and ADI sits strictly below — the f64 leg of
#: the CI gate asserts exactly that).
ACCURACY_MARGIN = 1.5


def accuracy_floor(dtype) -> float:
    """Roundoff floor for the matched-accuracy verdict: ~400 eps
    relative L2 (f32: ~5e-5; f64: ~9e-14, i.e. inert)."""
    return 400.0 * float(np.finfo(np.dtype(dtype)).eps)


def modeled_wall_s(method: str, nx: int, ny: int, steps: int,
                   unit_mcells_per_s: float = 1000.0) -> float:
    """Modeled time-to-solution: steps x per-step sweep units x the
    per-sweep cell cost. The rate cancels out of every speedup ratio —
    it only scales the absolute numbers."""
    units = STEP_UNITS[method]
    return steps * units * nx * ny / (unit_mcells_per_s * 1e6)


def _run_leg(method: str, u0, steps: int, cx: float, cy: float,
             use_kernels: bool):
    """One timed leg: (final grid, elapsed_s). The runner is built per
    leg and jitted; timing excludes compile/warmup (the reference
    protocol, utils/timing.timed_call)."""
    import jax
    import jax.numpy as jnp

    from heat2d_tpu.models import engine
    from heat2d_tpu.ops.stencil import stencil_step
    from heat2d_tpu.utils.timing import timed_call

    u0 = jnp.asarray(u0)
    if method == "explicit":
        if use_kernels:
            from heat2d_tpu.models.ensemble import _run_batch_band

            def run(u):
                c = jnp.full((1,), cx, u.dtype)
                d = jnp.full((1,), cy, u.dtype)
                return _run_batch_band(u[None], c, d, steps=steps)[0]
        else:
            def run(u):
                return engine.run_fixed(
                    lambda v: stencil_step(v, cx, cy,
                                           accum_dtype=None),
                    u, steps)[0]
    elif method == "adi":
        from heat2d_tpu.ops import tridiag as td
        if use_kernels and td.adi_kernel_viable(*u0.shape, u0.dtype):
            def run(u):
                c = jnp.full((1,), cx, u.dtype)
                d = jnp.full((1,), cy, u.dtype)
                return td.batched_adi_kernel(u[None], c, d,
                                             steps=steps)[0]
        else:
            def run(u):
                return td.adi_multi_step(u, steps, cx, cy)
    elif method == "mg":
        from heat2d_tpu.ops import multigrid as mgrid

        def run(u):
            return mgrid.mg_multi_step(u, steps, cx, cy)
    else:
        raise ValueError(f"unknown method {method!r}")

    fn = jax.jit(run)
    out, elapsed = timed_call(fn, u0)
    return np.asarray(out), float(elapsed)


def time_to_solution(nx: int, ny: int, *, steps_explicit: int,
                     step_ratio: int, cx: float = 0.2, cy: float = 0.2,
                     methods=("explicit", "adi"), dtype=np.float32,
                     use_kernels: bool = False,
                     registry=None) -> dict:
    """Run every method to the same ``t_final`` and compare.

    The explicit leg runs ``steps_explicit`` steps at (cx, cy) —
    validated against the stability box, the implicit legs skip the
    check — and each implicit leg runs ``steps_explicit //
    step_ratio`` steps at ``step_ratio``x the diffusion numbers: the
    same dimensionless physical time ``that = c * steps`` on both
    axes. Returns ``{"rows": [...], "summary": {...}}`` — the
    ``time_to_solution`` block of bench records (bench.py,
    docs/ALGORITHMS.md)."""
    if step_ratio < 1:
        raise ValueError(f"step_ratio must be >= 1, got {step_ratio}")
    that_x = cx * steps_explicit
    that_y = cy * steps_explicit
    u0 = analytic.separable_mode(nx, ny, dtype)
    ref = analytic.mode_solution(nx, ny, that_x, that_y, np.float64)

    rows = []
    for method in methods:
        if method == "explicit":
            steps, lcx, lcy = steps_explicit, cx, cy
            # The explicit route's guard (ops/stability.py): a clear
            # ConfigError naming the limit, BEFORE a diverging run.
            check_explicit_stability(lcx, lcy,
                                     where="time-to-solution explicit "
                                           "leg")
        else:
            steps = max(1, steps_explicit // step_ratio)
            lcx, lcy = that_x / steps, that_y / steps
        u, elapsed = _run_leg(method, u0, steps, lcx, lcy, use_kernels)
        rows.append({
            "method": method,
            "steps": steps,
            "cx": lcx, "cy": lcy,
            "time_to_solution_s": elapsed,
            "modeled_s": modeled_wall_s(method, nx, ny, steps),
            "accuracy": analytic.l2_error(u, ref),
        })

    by = {r["method"]: r for r in rows}
    summary = {"nx": nx, "ny": ny, "that_x": that_x, "that_y": that_y,
               "dtype": np.dtype(dtype).name}
    if "explicit" in by:
        exp = by["explicit"]
        for method, r in by.items():
            if method == "explicit":
                continue
            tag = method
            summary[f"{tag}_steps_ratio"] = exp["steps"] / r["steps"]
            summary[f"{tag}_wall_speedup"] = (
                exp["time_to_solution_s"] / r["time_to_solution_s"]
                if r["time_to_solution_s"] > 0 else float("nan"))
            summary[f"{tag}_modeled_speedup"] = (
                exp["modeled_s"] / r["modeled_s"])
            summary[f"{tag}_matched_accuracy"] = bool(
                r["accuracy"] <= max(ACCURACY_MARGIN * exp["accuracy"],
                                     accuracy_floor(dtype)))
    if registry is not None:
        if "adi" in by:
            registry.gauge("adi_time_to_solution_s",
                           by["adi"]["time_to_solution_s"])
            if "adi_wall_speedup" in summary:
                registry.gauge("adi_wall_speedup",
                               summary["adi_wall_speedup"])
        if "mg" in by:
            registry.gauge("mg_time_to_solution_s",
                           by["mg"]["time_to_solution_s"])
    return {"rows": rows, "summary": summary}


def bench_tts(quick: bool = False, on_tpu: bool = False,
              registry=None) -> dict:
    """The bench.py / tpu_smoke.py shape of the comparison: explicit
    at the stability edge vs ADI at 256x the step size, grid sized so
    the explicit leg stays a sub-second side measurement beside the
    headline Mcells/s run."""
    nx = ny = 257 if quick else 513
    steps = 640 if quick else 2560
    return time_to_solution(
        nx, ny, steps_explicit=steps, step_ratio=256,
        cx=0.2, cy=0.2, use_kernels=on_tpu, registry=registry)
