"""Ensemble runs — batch data-parallelism over problem instances.

The reference solves exactly one problem instance per launch (SURVEY.md
§2.3: "DP over batch / replicas — ABSENT"); parameter sweeps in Report.pdf
were separate compiles/runs per configuration. This module adds the
capability the survey flags as the natural TPU extension, as a real mode
of the framework (CLI: ``--ensemble-cx/--ensemble-cy``):

- ``jnp`` method: ``vmap`` the whole time loop over the (cx, cy) batch —
  one compiled program advances every member in lockstep.
- ``pallas`` method: one kernel launch for the whole batch — the program
  grid walks members, each VMEM-resident, with its (cx, cy) pair riding
  as an SMEM scalar block (the diffusivities are traced per-member
  values, so they are kernel *operands* here, not the baked constants the
  single-instance kernels use).
- ``band`` method: HBM-sized members stream through the temporally-
  blocked band kernel (pallas_stencil kernel C) over a (member, band)
  program grid — 'auto' routes here when a member exceeds the VMEM
  budget, so big members get the same kernel class as mode='pallas'
  instead of a vmap fallback.
- ``run_ensemble_sharded``: the batch as a mesh axis — members shard
  across devices (`shard_map` over a 1D 'b' mesh, batch padded to a
  device multiple with inert members), each device advancing its members
  through the same single-chip paths. This is DP over replicas on ICI;
  each member must fit one device's HBM.
- ``run_ensemble_spatial``: batch x spatial composition for members
  BIGGER than one device — a ('b', 'x', 'y') mesh where each member is
  spatially decomposed over its own (gridx, gridy) submesh (the dist2d
  wide-halo scheme, vmapped over the device's local members).

This is how the reference's Table-4-style parameter studies collapse into
a single launch.
"""

from __future__ import annotations

import functools
import types

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from heat2d_tpu.models import engine
from heat2d_tpu.ops.init import inidat
from heat2d_tpu.ops.stencil import residual_sq, stencil_step


def _validated_batch(nx, ny, cxs, cys, u0):
    cxs = jnp.asarray(cxs, jnp.float32)
    cys = jnp.asarray(cys, jnp.float32)
    if cxs.shape != cys.shape or cxs.ndim != 1:
        raise ValueError("cxs and cys must be equal-length 1D arrays")
    if u0 is None:
        u0 = jnp.broadcast_to(inidat(nx, ny), (cxs.shape[0], nx, ny))
    u0 = jnp.asarray(u0)
    if u0.shape != (cxs.shape[0], nx, ny):
        raise ValueError(
            f"u0 must be ({cxs.shape[0]}, {nx}, {ny}), got {u0.shape}")
    return cxs, cys, u0


def _run_batch_jnp(u0, cxs, cys, *, steps):
    def solve_one(u, cx, cy):
        u, _ = engine.run_fixed(lambda v: stencil_step(v, cx, cy), u, steps)
        return u

    return jax.vmap(solve_one)(u0, cxs, cys)


def _ensemble_kernel(s_ref, u_ref, out_ref, *, steps):
    from heat2d_tpu.ops.pallas_stencil import _step_value
    cx = s_ref[0, 0, 0]
    cy = s_ref[0, 0, 1]
    u = u_ref[0]
    u = jax.lax.fori_loop(0, steps,
                          lambda _, v: _step_value(v, cx, cy), u,
                          unroll=False)
    out_ref[0] = u


def _run_batch_pallas(u0, cxs, cys, *, steps):
    """One pallas_call for the whole batch: program grid over members,
    each member's grid VMEM-resident for all ``steps`` (the
    multi_step_vmem design batched; members must individually pass
    fits_vmem — callers route)."""
    from jax.experimental import pallas as pl
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid)

    b, nx, ny = u0.shape
    # (B, 1, 2): a (1, 1, 2) block's last two dims equal the array's —
    # a (1, 2) block over (B, 2) violates the Mosaic block rule for
    # B > 1 (caught on real TPU only; interpret mode accepts it).
    scal = jnp.stack([cxs, cys], axis=1)[:, None, :]
    mspace, smem = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, 2), lambda i: (i, 0, 0), **smem),
            pl.BlockSpec((1, nx, ny), lambda i: (i, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((1, nx, ny), lambda i: (i, 0, 0), **mspace),
    )
    return pl.pallas_call(
        functools.partial(_ensemble_kernel, steps=steps),
        out_shape=jax.ShapeDtypeStruct(u0.shape, u0.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        **_parallel_grid(1))(scal, u0)


def _ensemble_band_kernel(s_ref, up_ref, u_ref, dn_ref, out_ref, *,
                          bm, tsteps, nx, ny):
    """Temporally-blocked band sweep with per-member (cx, cy) scalars —
    pallas_stencil._band_multi_kernel with the diffusivities as SMEM
    operands (traced per-member values) instead of baked constants, over
    a (member, band) program grid."""
    from heat2d_tpu.ops.pallas_stencil import _step_value, _unrolled_steps

    j = pl.program_id(1)
    cx = s_ref[0, 0, 0]
    cy = s_ref[0, 0, 1]
    ext = jnp.concatenate([up_ref[0, 0], u_ref[0], dn_ref[0, 0]], axis=0)
    gi = (j * bm - tsteps
          + jax.lax.broadcasted_iota(jnp.int32, (bm + 2 * tsteps, 1), 0))
    keep = (gi <= 0) | (gi >= nx - 1)
    out_ref[0] = _unrolled_steps(
        tsteps, lambda v: jnp.where(keep, v, _step_value(v, cx, cy)),
        ext)[tsteps:-tsteps]


def _batched_band_sweep(scal, u, bm, tsteps, nx, ny):
    """One T-step sweep of every member's bands: grid (B, nblk), member
    blocks aliased in place (each program reads only its own block; the
    neighbor-row strips ride as separate operands)."""
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid, _row_strips)

    b, m, n = u.shape
    nblk = m // bm
    t = tsteps
    zeros = jnp.zeros((b, 1, t, n), u.dtype)
    ups, dns = _row_strips(u.reshape(b, nblk, bm, n), t, zeros, zeros)
    mspace, smem = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, 2), lambda i, j: (i, 0, 0), **smem),
            pl.BlockSpec((1, 1, t, n), lambda i, j: (i, j, 0, 0), **mspace),
            pl.BlockSpec((1, bm, n), lambda i, j: (i, j, 0), **mspace),
            pl.BlockSpec((1, 1, t, n), lambda i, j: (i, j, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((1, bm, n), lambda i, j: (i, j, 0), **mspace),
    )
    return pl.pallas_call(
        functools.partial(_ensemble_band_kernel, bm=bm, tsteps=tsteps,
                          nx=nx, ny=ny),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        input_output_aliases={2: 0},
        **_parallel_grid(2))(scal, ups, u, dns)


def _ens_window_kernel(s_ref, u_ref, out_ref, tail, *, bm, tsteps, nsub,
                       nx, hi_start):
    """Gather-free batched window sweep (kernel C2 with a member axis):
    the grid walks (member, band) pairs flattened into one SEQUENTIAL
    axis, down-strips ride in the row-overlapping element window,
    up-strips relay through the persistent scratch. At each member
    boundary the scratch holds the PREVIOUS member's tail — garbage for
    the new member's band 0, whose up rows sit at gi <= 0 where the
    keep mask firewalls it (exactly C2's uninitialized-scratch program
    0). Per-member (cx, cy) ride as SMEM scalars (traced operands, like
    the legacy _ensemble_band_kernel); the interior fast path uses a
    TRACED predicate on the member-local band index (the D2 scheme)."""
    from heat2d_tpu.ops.pallas_stencil import (_step_value, _unrolled_steps,
                                               _window_steps)

    j = pl.program_id(1)              # member-local band index
    t = tsteps
    cx = s_ref[0, 0, 0]
    cy = s_ref[0, 0, 1]
    up = tail[:]
    tail[:] = u_ref[0, bm - t:bm, :]
    ext = jnp.concatenate([up, u_ref[0]], axis=0)
    gi = (j * bm - t
          + jax.lax.broadcasted_iota(jnp.int32, (bm + 2 * t, 1), 0))
    keep = (gi <= 0) | (gi >= nx - 1)

    def masked(v):
        return jnp.where(keep, v, _step_value(v, cx, cy))

    if hi_start is None:
        if nsub < tsteps:
            # Partial-depth remainder sweeps ROLL their short step
            # loop: the batched kernel's inlined stack at full bm blows
            # Mosaic's scoped VMEM (18.24 MB at bm=320/8 KB rows for a
            # 4-step inline that the single-instance kernel fits).
            # Once-per-chunk tails; the cross-step unroll win is
            # irrelevant there.
            out_ref[0] = jax.lax.fori_loop(
                0, nsub, lambda _, w: masked(w), ext,
                unroll=False)[t:-t]
        else:
            out_ref[0] = _window_steps(nsub, masked, ext)[t:-t]
        return
    needs = (j == 0) | (j >= hi_start)

    @pl.when(needs)
    def _():
        out_ref[0] = _unrolled_steps(t, masked, ext)[t:-t]

    @pl.when(jnp.logical_not(needs))
    def _():
        out_ref[0] = _unrolled_steps(
            t, lambda v: _step_value(v, cx, cy), ext)[t:-t]


def _batched_window_sweep(scal, u, bm, tsteps, nblk, nx, nsub=None):
    """One sweep of every member's bands over the (B, m_pad + T, ny)
    carry (each member the C2 padded sweep layout). 2D (member, band)
    grid, both axes sequential (row-major: bands run in order within a
    member — the relay's dataflow edge). The member window rides as an
    ALL-Element 3D spec — mixing Blocked and Element dims in one spec
    is unimplemented on this pallas, and a flattened 1D grid would need
    i//nblk in the index maps, which Mosaic's window inference rejects
    (every bm failed to compile, not just deep ones)."""
    from heat2d_tpu.ops.pallas_stencil import (_compiler_params_cls,
                                               _mem_spaces)

    t = tsteps
    b, _, ny = u.shape
    hi_start = None
    if nsub is None or nsub == tsteps:
        from heat2d_tpu.ops.pallas_stencil import _mask_hi_start
        hs = _mask_hi_start(nx, bm, t)
        hi_start = hs if hs > 1 else None
    mspace, smem = _mem_spaces()
    params = _compiler_params_cls()
    return pl.pallas_call(
        functools.partial(_ens_window_kernel, bm=bm, tsteps=t,
                          nsub=tsteps if nsub is None else nsub,
                          nx=nx, hi_start=hi_start),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, 2), lambda b, i: (b, 0, 0), **smem),
            pl.BlockSpec((pl.Element(1), pl.Element(bm + t),
                          pl.Element(ny)),
                         lambda b, i: (b, i * bm, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((1, bm, ny), lambda b, i: (b, i, 0),
                               **mspace),
        scratch_shapes=[_pltpu_vmem((t, ny), u.dtype)],
        input_output_aliases={1: 0},
        compiler_params=params(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(scal, u)


def _pltpu_vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, dtype)


def _ens_conv_kernel(resid, s_ref, act_ref, u_ref, *refs, bm, tsteps,
                     nsub, nx):
    """Batched window sweep for the CONVERGENCE route: per-member
    ``act`` flags ride in SMEM and frozen (converged) members' programs
    skip the step computation entirely, writing their block through
    unchanged. This keeps the per-member freeze INSIDE the kernel: an
    outer jnp.where(done, u, v) select makes the carry a second
    consumer of the aliased sweep operand, which breaks XLA's alias
    chain and deterministically OOMs Mosaic's scoped VMEM at full band
    depth (18.24 MB at bm=320/8 KB — the round-5 finding); it also
    means converged members stop consuming VPU at all. One uniform
    masked body (no interior fast path): the active/frozen pl.when pair
    already doubles the body count, and dual fast-path bodies of
    inlined steps are the known scoped-VMEM stack hazard."""
    from heat2d_tpu.ops.pallas_stencil import _step_value, _window_steps

    if resid:
        out_ref, r_ref, tail = refs
    else:
        out_ref, tail = refs
    j = pl.program_id(1)
    t = tsteps
    cx = s_ref[0, 0, 0]
    cy = s_ref[0, 0, 1]
    up = tail[:]
    # Stash unconditionally: frozen members' relay data is never read
    # (their bands skip the ext assembly), and the stash must not
    # depend on a traced predicate.
    tail[:] = u_ref[0, bm - t:bm, :]
    active = act_ref[0, 0, 0] != 0

    @pl.when(active)
    def _():
        ext = jnp.concatenate([up, u_ref[0]], axis=0)
        gi = (j * bm - t
              + jax.lax.broadcasted_iota(jnp.int32, (bm + 2 * t, 1), 0))
        keep = (gi <= 0) | (gi >= nx - 1)

        def masked(v):
            return jnp.where(keep, v, _step_value(v, cx, cy))

        if resid:
            # nsub <= t: the chunk-tail resid schedule (every other
            # sweep of the chunk stays a full fast one).
            v = ext
            for _ in range(nsub - 1):
                v = masked(v)
            prev = v
            last = masked(v)
            out_ref[0] = last[t:-t]
            d = last[t:-t] - prev[t:-t]
            r_ref[...] = jnp.sum(d * d).reshape(1, 1, 1, 1)
        elif nsub < tsteps:
            # Rolled short loop — the batched inline stack at full bm
            # is the scoped-VMEM hazard; once-per-chunk tails.
            out_ref[0] = jax.lax.fori_loop(
                0, nsub, lambda _, w: masked(w), ext,
                unroll=False)[t:-t]
        else:
            out_ref[0] = _window_steps(nsub, masked, ext)[t:-t]

    @pl.when(jnp.logical_not(active))
    def _():
        out_ref[0] = u_ref[0, :bm, :]
        if resid:
            r_ref[...] = jnp.zeros((1, 1, 1, 1), jnp.float32)


def _batched_conv_sweep(scal, act, u, bm, tsteps, nblk, nx, nsub=None,
                        resid=False):
    """One convergence-route sweep (act-gated): returns u_new, or
    (u_new, per-member res) when ``resid``."""
    from heat2d_tpu.ops.pallas_stencil import (_compiler_params_cls,
                                               _mem_spaces)

    t = tsteps
    b, _, ny = u.shape
    mspace, smem = _mem_spaces()
    params = _compiler_params_cls()
    out_shape = [jax.ShapeDtypeStruct(u.shape, u.dtype)]
    out_specs = [pl.BlockSpec((1, bm, ny), lambda b, i: (b, i, 0),
                              **mspace)]
    if resid:
        out_shape.append(
            jax.ShapeDtypeStruct((b, nblk, 1, 1), jnp.float32))
        out_specs.append(pl.BlockSpec((1, 1, 1, 1),
                                      lambda b, i: (b, i, 0, 0),
                                      **mspace))
    out = pl.pallas_call(
        functools.partial(_ens_conv_kernel, resid, bm=bm, tsteps=t,
                          nsub=t if nsub is None else nsub, nx=nx),
        out_shape=out_shape if resid else out_shape[0],
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, 2), lambda b, i: (b, 0, 0), **smem),
            pl.BlockSpec((1, 1, 1), lambda b, i: (b, 0, 0), **smem),
            pl.BlockSpec((pl.Element(1), pl.Element(bm + t),
                          pl.Element(ny)),
                         lambda b, i: (b, i * bm, 0), **mspace),
        ],
        out_specs=out_specs if resid else out_specs[0],
        scratch_shapes=[_pltpu_vmem((t, ny), u.dtype)],
        input_output_aliases={2: 0},
        compiler_params=params(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(scal, act, u)
    if resid:
        return out[0], jnp.sum(out[1], axis=(1, 2, 3))
    return out


#: Measured BATCHED window-sweep compile envelope (v5e, T=8): max ext
#: rows per member row width — tighter than single-instance C2's table
#: at 16 KB (bm=120 compiles, 128-152 OOM ~1.9-2.2 MB over; at 8 KB the
#: full 336 holds). Widths off this table keep the legacy batched
#: band route (gathered strips).
_ENS_WINDOW_EXT_ROWS = {8 * 1024: 336, 16 * 1024: 136}

#: Measured batched-RESID compile envelope (v5e, T=8): the resid sweep
#: is single-body (no dual fast path), so its 16 KB break sits slightly
#: higher (bm=128 fits; bm=152 OOMs). Widths off this table keep the
#: unfused pair-tracked convergence loop.
_ENS_RESID_EXT_ROWS = {8 * 1024: 336, 16 * 1024: 144}


def _ens_plan_window(nx, ny, t, dtype):
    """(bm, m_pad) for the batched window route, or None when the
    member width is off the probed batched envelope (legacy route) —
    the ONE plan the fixed-step and convergence batched routes share."""
    from heat2d_tpu.ops import pallas_stencil as ps

    ext = ps._probed_table_ext_rows(
        _ENS_WINDOW_EXT_ROWS, ny * jnp.dtype(dtype).itemsize)
    if ext is None:
        return None
    bm, m_pad = ps.plan_from_ext(nx, ext, t)
    if not ps.window_band_viable(ny, bm, t):
        return None
    return bm, m_pad


def _ens_resid_bm(m_pad, bm, row_bytes, t):
    """Band height for the fused resid sweep: the largest 8-aligned
    DIVISOR of m_pad within the probed resid envelope (the sweep must
    tile the plan's carry layout exactly), capped by the plan bm. None
    -> no viable fused resid (caller keeps the unfused loop). The
    lookup goes through the shared device/override gating like every
    probed table (review r5)."""
    from heat2d_tpu.ops import pallas_stencil as ps

    ext = ps._probed_table_ext_rows(_ENS_RESID_EXT_ROWS, row_bytes)
    if ext is None:
        return None
    cap = min(bm, ext - 2 * t)
    for b2 in range(cap - cap % 8, 2 * t, -8):
        if m_pad % b2 == 0:
            return b2
    return None


def _emit_members(tap, chunk, chunks, res, done) -> None:
    """Chunk-progress stream for the batched convergence loops: one
    ``jax.debug.callback`` per chunk with the per-member state vectors
    (steps-done, residuals, done flags) — obs/stream.TelemetryStream.
    tap_members is the standard collector. Python-level guard: tap=None
    adds zero equations (the no-overhead guarantee the tests pin), so
    call sites guard any argument computed only for telemetry (e.g.
    ``chunks * interval``) behind their own ``tap is not None``."""
    if tap is not None:
        jax.debug.callback(tap, chunk, chunks, res, done, ordered=False)


def _flush_taps() -> None:
    """Drain queued ``jax.debug.callback`` work so a collector read
    immediately after a run sees every chunk (the callbacks are
    fire-and-forget and may still be in flight when the outputs are
    ready)."""
    from heat2d_tpu.obs.stream import flush_taps
    flush_taps()


def _run_batch_conv_window(u0, cxs, cys, *, steps, interval, sensitivity,
                           bm, m_pad, t, resid_bm, tap=None):
    """Fused-residual convergence for window-routed HBM members: each
    chunk's residual folds into its last sweep (the C2R schedule,
    member-wise) instead of the pair-tracked chunk(n-1)+chunk(1)+
    full-grid vmapped reduction — measured 0.78x batching efficiency on
    the unfused loop at 2560x2048/B=4. The padded carry persists across
    the whole while loop; per-member freeze/early-exit semantics are
    identical to _run_batch_conv_kernel (residual summation order
    differs at f32-ulp, the C2R deviation class)."""
    b, nx, ny = u0.shape
    nblk = m_pad // bm
    iv = max(1, min(interval, steps)) if steps else interval
    n_chunks = steps // iv if iv else 0
    remainder = steps - n_chunks * iv
    scal = jnp.stack([cxs, cys], axis=1)[:, None, :]
    u = jnp.pad(u0, ((0, 0), (0, m_pad - nx + t), (0, 0)))

    def act_of(done):
        return jnp.logical_not(done).astype(jnp.int32)[:, None, None]

    def multi(v, n, act):
        nsweeps, rem = divmod(n, t)
        if nsweeps:
            v = jax.lax.fori_loop(
                0, nsweeps,
                lambda _, w: _batched_conv_sweep(scal, act, w, bm, t,
                                                 nblk, nx),
                v, unroll=False)
        if rem:
            v = _batched_conv_sweep(scal, act, v, bm, t, nblk, nx,
                                    nsub=rem)
        return v

    def body(carry):
        u, i, chunks, done = carry
        act = act_of(done)
        d = iv % t or t      # chunk-tail resid depth
        u = multi(u, iv - d, act)
        u, res = _batched_conv_sweep(scal, act, u, resid_bm, t,
                                     m_pad // resid_bm, nx, nsub=d,
                                     resid=True)
        # Frozen members wrote through unchanged in-kernel (no outer
        # select: a second consumer of the carry breaks the alias
        # chain — see _ens_conv_kernel) and report res=0, which cannot
        # un-converge them (done is a monotone union).
        chunks = jnp.where(done, chunks, chunks + 1)
        done = done | (res < sensitivity)
        i = i + 1
        if tap is not None:   # chunks * iv is telemetry-only
            _emit_members(tap, i, chunks * iv, res, done)
        return (u, i, chunks, done)

    def cond(carry):
        _, i, _, done = carry
        return jnp.logical_and(i < n_chunks,
                               jnp.logical_not(jnp.all(done)))

    init = (u, jnp.asarray(0, jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool))
    u, _, chunks, done = jax.lax.while_loop(cond, body, init)
    k = (chunks * iv).astype(jnp.int32)
    if remainder:
        u = multi(u, remainder, act_of(done))
        k = jnp.where(done, k, k + remainder).astype(jnp.int32)
    return u[:, :nx], k


def _band_conv_runner(u0, cxs, cys, *, steps, interval, sensitivity,
                      tap=None):
    """Convergence runner for method='band': the fused window path when
    its gates hold (TPU, lane-aligned width, on-table batched envelope;
    any interval >= 1 since the chunk-tail resid schedule), else the
    generic pair-tracked chunked loop over the band runner."""
    from heat2d_tpu.ops import pallas_stencil as ps

    _, nx, ny = u0.shape
    t = ps.DEFAULT_TSTEPS
    # Any interval >= 1 is viable since the round-5 chunk-tail resid
    # schedule (the resid sweep's depth adapts to the chunk tail).
    if ps._on_tpu() and ny % 128 == 0:
        plan = _ens_plan_window(nx, ny, t, u0.dtype)
        if plan is not None:
            bm, m_pad = plan
            rbm = _ens_resid_bm(m_pad, bm,
                                ny * jnp.dtype(u0.dtype).itemsize, t)
            if rbm is not None:
                # Mirror _run_batch_band: fast-fail unprobed configs on
                # the working-set check instead of an opaque Mosaic
                # scoped-VMEM OOM (advisor r5).
                ps._check_band_vmem(bm, t, ny, u0.dtype)
                return _run_batch_conv_window(
                    u0, cxs, cys, steps=steps, interval=interval,
                    sensitivity=sensitivity, bm=bm, m_pad=m_pad, t=t,
                    resid_bm=rbm, tap=tap)
    return _run_batch_conv_kernel(u0, cxs, cys, steps=steps,
                                  interval=interval,
                                  sensitivity=sensitivity,
                                  runner=_run_batch_band, tap=tap)


def _run_batch_window(u0, cxs, cys, *, steps, bm, m_pad, t):
    """Gather-free window route for HBM-sized members: the round-4 C2
    copy elimination (+20% single-instance) applied to the batch — the
    legacy route re-gathered (B, nblk, T, ny) strips every sweep
    (VERDICT r4 weak #2)."""
    b, nx, ny = u0.shape
    nblk = m_pad // bm
    u = jnp.pad(u0, ((0, 0), (0, m_pad - nx + t), (0, 0)))
    scal = jnp.stack([cxs, cys], axis=1)[:, None, :]   # (B, 1, 2)
    nsweeps, rem = divmod(steps, t)
    if nsweeps:
        u = jax.lax.fori_loop(
            0, nsweeps,
            lambda _, v: _batched_window_sweep(scal, v, bm, t, nblk, nx),
            u, unroll=False)
    if rem:
        u = _batched_window_sweep(scal, u, bm, t, nblk, nx, nsub=rem)
    return u[:, :nx]


def _run_batch_band(u0, cxs, cys, *, steps):
    """HBM-sized members: every member streamed through band sweeps in
    one launch. Routes to the gather-free batched WINDOW kernel (the C2
    scheme with a member axis) when its Mosaic constraints hold; the
    legacy gathered-strip kernel keeps interpreter mode and misaligned
    shapes. Closes the VERDICT r2 weak-#3 gap (members too big for VMEM
    fell back to the vmap'd jnp path) and the r4 weak-#2 gap (the
    legacy route's per-sweep strip re-gather)."""
    from heat2d_tpu.ops import pallas_stencil as ps

    b, nx, ny = u0.shape
    t = ps.DEFAULT_TSTEPS
    if ps._on_tpu() and ny % 128 == 0 and t % 8 == 0:
        plan = _ens_plan_window(nx, ny, t, u0.dtype)
        if plan is not None:
            bm, m_pad = plan
            ps._check_band_vmem(bm, t, ny, u0.dtype)
            return _run_batch_window(u0, cxs, cys, steps=steps, bm=bm,
                                     m_pad=m_pad, t=t)
    # band_plan wraps _resolve_bands, not plan_bands: with a tuning db
    # active the member-shape's measured bm replaces the heuristic
    # (validated against the resource model by the hook); without one
    # this IS plan_bands, program-identical. The shared plan is also
    # what the IR verifier checks traced strip depths against.
    bm, m_pad, t, _ = ps.band_plan(nx, ny, u0.dtype, tsteps=t)
    u = u0
    if m_pad > nx:
        u = jnp.pad(u, ((0, 0), (0, m_pad - nx), (0, 0)))
    scal = jnp.stack([cxs, cys], axis=1)[:, None, :]   # (B, 1, 2)
    nsweeps, rem = divmod(steps, t)
    if nsweeps:
        u = jax.lax.fori_loop(
            0, nsweeps,
            lambda _, v: _batched_band_sweep(scal, v, bm, t, nx, ny), u,
            unroll=False)
    if rem:
        u = _batched_band_sweep(scal, u, bm, rem, nx, ny)
    return u[:, :nx] if m_pad > nx else u


def _run_batch_adi(u0, cxs, cys, *, steps):
    """Implicit route: Crank-Nicolson ADI (Peaceman-Rachford) with
    batched tridiagonal Thomas solves (ops/tridiag.py). The (cx, cy)
    here are the ADI step's diffusion numbers — unconditionally
    stable, so they may sit far past the explicit kx+ky <= 1/2 box:
    that is the whole point (100-1000x fewer steps to the same
    physical time, docs/ALGORITHMS.md). Kernel TD on a viable TPU
    shape; the scan route (correct everywhere) otherwise."""
    from heat2d_tpu.ops import tridiag as td

    _, nx, ny = u0.shape
    if td.adi_kernel_viable(nx, ny, u0.dtype):
        return td.batched_adi_kernel(u0, cxs, cys, steps=steps)
    return td.batched_adi_scan(u0, cxs, cys, steps=steps)


def _run_batch_mg(u0, cxs, cys, *, steps):
    """Implicit route: unsplit Crank-Nicolson stepped by geometric
    multigrid V-cycles (ops/multigrid.py) — the preconditioned
    iterative route for the steady/convergence path; the existing
    stencil kernel is the smoother. vmapped per member (the V-cycle
    recursion is static, so the batch shares one program)."""
    from heat2d_tpu.ops import multigrid as mgrid

    cxs = jnp.asarray(cxs, u0.dtype)
    cys = jnp.asarray(cys, u0.dtype)

    def one(u, cx, cy):
        return mgrid.mg_multi_step(u, steps, cx, cy)

    return jax.vmap(one)(u0, cxs, cys)


_BATCH_RUNNERS = {"jnp": _run_batch_jnp, "pallas": _run_batch_pallas,
                  "band": _run_batch_band, "adi": _run_batch_adi,
                  "mg": _run_batch_mg}


# --------------------------------------------------------------------- #
# Convergence (early-exit) ensembles
# --------------------------------------------------------------------- #

def _run_batch_conv_jnp(u0, cxs, cys, *, steps, interval, sensitivity):
    """vmap of the engine convergence loop: JAX's while_loop batching
    rule gives masked completion for free — the combined loop runs while
    ANY member's predicate holds and select-freezes finished lanes, so
    each member's trajectory (and steps_done) is exactly its individual
    engine.run_convergence trajectory (the per-member bitwise-parity
    tests pin this)."""
    def solve_one(u, cx, cy):
        return engine.run_convergence(
            lambda v: stencil_step(v, cx, cy), residual_sq,
            u, steps, interval, sensitivity)

    return jax.vmap(solve_one)(u0, cxs, cys)


def _run_batch_conv_kernel(u0, cxs, cys, *, steps, interval, sensitivity,
                           runner, tap=None):
    """Batched engine.run_convergence_chunked over the kernel runners:
    each chunk is ``interval-1`` fused steps plus one tracked step; the
    residual is per-member; converged members freeze (their stored plane
    stops updating) while the rest continue, and the loop exits when all
    members converge or the chunk budget is spent. The trailing
    ``steps % interval`` remainder runs unchecked on unconverged members
    only — the same schedule as the individual chunked loop, member-wise.
    """
    if steps:
        interval = max(1, min(interval, steps))
    n_chunks = steps // interval if interval else 0
    remainder = steps - n_chunks * interval
    b = u0.shape[0]

    def chunk(u, n):
        return runner(u, cxs, cys, steps=n)

    def body(carry):
        u, i, chunks, done = carry
        u_prev = chunk(u, interval - 1) if interval > 1 else u
        u_new = chunk(u_prev, 1)
        # vmap'd residual_sq so the per-member residual is the SAME
        # definition (cast order included) the individual loops use.
        res = jax.vmap(lambda a, b: residual_sq(a, b))(u_new, u_prev)
        # Members already done keep their frozen plane; the member that
        # converges THIS chunk stores u_new (matching the individual
        # loop, whose final plane is the one its residual was computed
        # from) and freezes starting next iteration.
        u = jnp.where(done[:, None, None], u, u_new)
        chunks = jnp.where(done, chunks, chunks + 1)
        done = done | (res < sensitivity)
        i = i + 1
        if tap is not None:   # chunks * interval is telemetry-only
            _emit_members(tap, i, chunks * interval, res, done)
        return (u, i, chunks, done)

    def cond(carry):
        _, i, _, done = carry
        return jnp.logical_and(i < n_chunks,
                               jnp.logical_not(jnp.all(done)))

    init = (u0, jnp.asarray(0, jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool))
    u, _, chunks, done = jax.lax.while_loop(cond, body, init)
    k = (chunks * interval).astype(jnp.int32)
    if remainder:
        u_adv = chunk(u, remainder)
        u = jnp.where(done[:, None, None], u, u_adv)
        k = jnp.where(done, k, k + remainder).astype(jnp.int32)
    return u, k


def _conv_runner(method, steps, interval, sensitivity, tap=None):
    """The jitted (u0, cxs, cys) -> (u, steps_done) convergence runner
    for a method — vmap'd engine loop for 'jnp', the batched chunked
    loop over the corresponding kernel runner otherwise.

    ``tap``: optional chunk-progress stream (_emit_members). The 'jnp'
    method ignores it: its while_loop is vmapped per member, and a
    callback under vmap would not see the batch coherently — the batched
    kernel loops are the streaming routes."""
    if method == "jnp":
        return functools.partial(_run_batch_conv_jnp, steps=steps,
                                 interval=interval,
                                 sensitivity=sensitivity)
    if method == "band":
        return functools.partial(_band_conv_runner, steps=steps,
                                 interval=interval,
                                 sensitivity=sensitivity, tap=tap)
    return functools.partial(_run_batch_conv_kernel, steps=steps,
                             interval=interval, sensitivity=sensitivity,
                             runner=_BATCH_RUNNERS[method], tap=tap)


def run_ensemble_convergence(nx: int, ny: int, steps: int, interval: int,
                             sensitivity: float, cxs, cys, u0=None,
                             method: str = "auto", tap=None,
                             problem: str = "heat5"):
    """Ensemble with per-member convergence early-exit — the intended
    grad1612_mpi_heat.c:262-271 residual schedule applied member-wise
    (the reference could only run one instance per launch; SURVEY.md
    §2.3). Returns (batch, steps_done): converged members froze at
    their exit plane; ``steps_done[i]`` is member i's iteration count,
    a multiple of ``interval`` unless the step budget ran out first.

    ``tap``: optional chunk-progress telemetry stream (see
    obs/stream.TelemetryStream.tap_members); honored by the batched
    kernel methods, ignored by 'jnp' (vmapped loop) and by registry
    families (``problem`` != "heat5", which run the generic chunked
    loop without a tap)."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    if problem != "heat5":
        fn = batch_runner(nx, ny, steps, method, convergence=True,
                          interval=interval, sensitivity=sensitivity,
                          problem=problem)
        return fn(u0, cxs, cys)
    method = _pick_method(method, nx, ny)
    fn = jax.jit(_conv_runner(method, steps, interval, sensitivity,
                              tap=tap))
    out = fn(u0, cxs, cys)
    if tap is not None:
        out = jax.block_until_ready(out)
        _flush_taps()
    return out


def _pick_method(method, nx, ny):
    if method != "auto":
        return method
    from heat2d_tpu.ops.pallas_stencil import fits_vmem
    return "pallas" if fits_vmem((nx, ny)) else "band"


@functools.lru_cache(maxsize=128)
def batch_runner(nx: int, ny: int, steps: int, method: str = "auto",
                 convergence: bool = False, interval: int = 20,
                 sensitivity: float = 0.1, problem: str = "heat5"):
    """The per-signature COMPILE-CACHED batch-of-heterogeneous-params
    entry: a jitted ``(u0, cxs, cys) -> batch`` (fixed-step) or
    ``-> (batch, steps_done)`` (convergence) runner, memoized by
    compiled signature so every later call reuses the SAME callable —
    and therefore XLA's already-built executable. ``jax.jit`` caches by
    function identity, so the per-call ``jax.jit(functools.partial(...))``
    the one-shot entry points build retraces every launch; this entry is
    what a long-lived caller (serve/engine.py) dispatches through so
    steady-state traffic on a warm signature never retraces. cxs/cys are
    traced operands — heterogeneous per-member diffusivities share one
    executable; only a new batch shape or dtype triggers a (cached)
    re-specialization inside the one jitted callable.

    ``problem``: the spatial-operator family (heat2d_tpu/problems/).
    The default "heat5" takes the pre-registry path below, byte-for-
    byte (jaxpr-pinned); other families dispatch to the registry's
    generic runners with route legality enforced against the declared
    capability matrix (problems.runners.pick_route)."""
    if problem != "heat5":
        from heat2d_tpu.problems import runners as prunners
        route = prunners.pick_route(problem, method, nx, ny)
        runner = prunners.fixed_runner(problem, route)
        if convergence:
            fn = functools.partial(_run_batch_conv_kernel, steps=steps,
                                   interval=interval,
                                   sensitivity=sensitivity,
                                   runner=runner)
        else:
            fn = functools.partial(runner, steps=steps)
        try:
            fn.__name__ = f"batch_runner_{problem}_{route}"
        except (AttributeError, TypeError):
            pass
        return jax.jit(fn)
    method = _pick_method(method, nx, ny)
    if convergence:
        fn = _conv_runner(method, steps, interval, sensitivity)
    else:
        fn = functools.partial(_BATCH_RUNNERS[method], steps=steps)
    # A stable name (partials log as "<unnamed wrapped function>"):
    # compile logs, traces, and the recompile sentinel
    # (analysis/recompile.py) attribute every serve compile to the
    # runner they belong to. Host-side metadata only — the traced
    # program is unchanged.
    try:
        fn.__name__ = f"batch_runner_{method}"
    except (AttributeError, TypeError):
        pass
    return jax.jit(fn)


def run_ensemble(nx: int, ny: int, steps: int, cxs, cys, u0=None,
                 method: str = "auto", problem: str = "heat5"):
    """Advance an ensemble of diffusivity pairs ``steps`` steps.

    ``cxs``/``cys``: 1D arrays of equal length B. ``u0``: optional (B, nx,
    ny) batch of initial grids; defaults to B copies of the reference
    initial condition (mpi_heat2Dn.c:242-248). Returns (B, nx, ny).

    ``method``: 'jnp' (vmap), 'pallas' (batched kernel, members must be
    VMEM-resident), 'band' (batched temporally-blocked band kernel for
    HBM-sized members), or 'auto' (pallas when a member fits VMEM, band
    otherwise).

    ``problem``: spatial-operator family — "heat5" (default, the
    pre-registry path, jaxpr-pinned) or any registered family, which
    dispatches through the registry's generic runners with the route
    validated against the declared capability matrix.
    """
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    if problem != "heat5":
        fn = batch_runner(nx, ny, steps, method, problem=problem)
        return fn(u0, cxs, cys)
    method = _pick_method(method, nx, ny)
    fn, args, b = _build_single(steps, method, u0, cxs, cys)
    return fn(*args)


def _build_single(steps, method, u0, cxs, cys):
    nx, ny = u0.shape[1], u0.shape[2]
    fn = batch_runner(nx, ny, steps, method)
    return fn, (u0, cxs, cys), cxs.shape[0]


def _shard_local_fn(local, u0, cxs, cys, devices):
    """Jitted shard_map program + placed inputs for a batch-axis mesh;
    pads the batch to a device multiple with inert members (cx=cy=0).
    ``local`` is any (u, cxs, cys) -> outputs batch function; each
    device runs it on its local members (device-local while_loops in the
    convergence case — no collective inside, so devices may exit their
    loops at different chunk counts)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from heat2d_tpu.parallel.mesh import shard_map_compat

    devices = list(devices if devices is not None else jax.devices())
    b, nx, ny = u0.shape
    nd = min(len(devices), b)
    devices = devices[:nd]
    pad = (-b) % nd
    if pad:
        cxs = jnp.concatenate([cxs, jnp.zeros((pad,), cxs.dtype)])
        cys = jnp.concatenate([cys, jnp.zeros((pad,), cys.dtype)])
        u0 = jnp.concatenate(
            [u0, jnp.zeros((pad, nx, ny), u0.dtype)], axis=0)

    mesh = Mesh(np.asarray(devices), ("b",))
    mapped = shard_map_compat(local, mesh, in_specs=P("b"),
                              out_specs=P("b"), check_vma=False)
    sharding = NamedSharding(mesh, P("b"))
    u0 = jax.device_put(u0, sharding)
    cxs = jax.device_put(cxs, sharding)
    cys = jax.device_put(cys, sharding)
    return jax.jit(mapped), (u0, cxs, cys), b


def _build_sharded(steps, method, u0, cxs, cys, devices):
    run = _BATCH_RUNNERS[method]

    def local(u, cx, cy):
        return run(u, cx, cy, steps=steps)

    return _shard_local_fn(local, u0, cxs, cys, devices)


def run_ensemble_sharded(nx: int, ny: int, steps: int, cxs, cys, u0=None,
                         method: str = "auto", devices=None):
    """Ensemble with the batch as a mesh axis: members shard over devices
    (DP over replicas — SURVEY.md §2.3), each device advancing its local
    members through the single-chip batch path. Returns (B, nx, ny)."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    method = _pick_method(method, nx, ny)
    fn, args, b = _build_sharded(steps, method, u0, cxs, cys, devices)
    return fn(*args)[:b]


def run_ensemble_convergence_sharded(nx: int, ny: int, steps: int,
                                     interval: int, sensitivity: float,
                                     cxs, cys, u0=None,
                                     method: str = "auto", devices=None):
    """Convergence ensemble with the batch as a mesh axis. Inert pad
    members (cx=cy=0) reach residual 0 after one chunk, so they converge
    immediately for any sensitivity > 0 and never hold their device's
    loop open (with sensitivity == 0 every member runs the full budget
    anyway). Returns (batch, steps_done), both cropped to B."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    method = _pick_method(method, nx, ny)
    local = _conv_runner(method, steps, interval, sensitivity)
    fn, args, b = _shard_local_fn(local, u0, cxs, cys, devices)
    u, k = fn(*args)
    return u[:b], k[:b]


# --------------------------------------------------------------------- #
# Batch x spatial composition: members bigger than one device's HBM
# --------------------------------------------------------------------- #

def spatial_halo_plan(nx, ny, gridx, gridy, halo="collective",
                      halo_depth=None) -> dict:
    """Pre-resolved halo-route plan for a batch x spatial signature —
    the fused-route twin of the serve engine's per-signature tuned-
    config resolve: route/tier/depth decided from the static geometry
    (and the tuning db's fused entry, when one is active) BEFORE
    anything compiles, so launch records can carry the plan the
    compiled program actually uses. Pure host-side math — no devices
    touched (the spatial axes ride in explicitly). TOTAL: a shape the
    decomposition cannot take (grid not divisible, too small) returns
    an error-carrying collective plan instead of raising — the resolve
    is advisory and must never fail a request the caller's actual
    (possibly single-device) runner serves fine."""
    from heat2d_tpu.config import ConfigError, HeatConfig
    from heat2d_tpu.parallel import sharded as sh

    try:
        cfg = HeatConfig(nxprob=nx, nyprob=ny, mode="dist2d",
                         gridx=gridx, gridy=gridy, halo=halo,
                         halo_depth=halo_depth)
    except ConfigError as e:
        return dict(requested=halo, route="collective",
                    tier="unplannable", depth=0, shard=None,
                    mesh=(gridx, gridy), error=str(e))
    return sh.resolve_halo_route(cfg, None,
                                 axes=("x", "y", gridx, gridy))


def _build_spatial(nx, ny, steps, gridx, gridy, u0, cxs, cys, devices,
                   convergence, interval, sensitivity, halo_depth=None,
                   halo="collective"):
    """Jitted runner + placed inputs for a 3-axis ('b', 'x', 'y') mesh:
    each member is spatially decomposed over a (gridx, gridy) submesh
    (the dist2d scheme — 4-neighbor wide-halo ppermute, VERDICT r3 weak
    #4's missing composition) while the batch shards over 'b'. Inside
    shard_map the member loop is a vmap over the device's local members,
    so the halo ppermutes and the per-member psum'd residual batch over
    the leading axis; per-member (cx, cy) ride as traced scalars through
    the jnp chunk path (sharded.make_local_chunk cxy=...). Convergence
    gives per-member early exit via the vmapped while_loop exactly as
    the single-chip batched loops do. Returns (fn, args, b)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.parallel import sharded as sh
    from heat2d_tpu.parallel.mesh import shard_map_compat

    b, _, _ = u0.shape
    devices = list(devices if devices is not None else jax.devices())
    spatial = gridx * gridy
    nb = len(devices) // spatial
    if nb < 1:
        raise ValueError(
            f"batch x spatial ensemble needs at least gridx*gridy = "
            f"{spatial} devices; have {len(devices)}")
    nb = min(nb, b)
    mesh = Mesh(np.asarray(devices[:nb * spatial]).reshape(
        nb, gridx, gridy), ("b", "x", "y"))
    axes = ("x", "y", gridx, gridy)

    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist2d",
                     gridx=gridx, gridy=gridy, convergence=convergence,
                     interval=interval, sensitivity=sensitivity,
                     halo_depth=halo_depth, halo=halo)
    pnx, pny = sh.padded_global_shape(cfg, mesh, axes)
    accum = jnp.float32

    pad = (-b) % nb
    if pad:       # inert members (cx=cy=0), cropped on return
        cxs = jnp.concatenate([cxs, jnp.zeros((pad,), cxs.dtype)])
        cys = jnp.concatenate([cys, jnp.zeros((pad,), cys.dtype)])
        u0 = jnp.concatenate(
            [u0, jnp.zeros((pad,) + u0.shape[1:], u0.dtype)], axis=0)
    if (pnx, pny) != (nx, ny):    # equal-shard spatial padding
        u0 = jnp.pad(u0, ((0, 0), (0, pnx - nx), (0, pny - ny)))

    def chunk(u, cx, cy, n):
        def one(ui, cxi, cyi):
            return sh.make_local_multi(cfg, mesh, axes=axes,
                                       cxy=(cxi, cyi))(ui, n)
        return jax.vmap(one)(u, cx, cy)

    def local(u, cx, cy):
        if not convergence:
            u = chunk(u, cx, cy, steps)
            return u, jnp.full(u.shape[:1], steps, jnp.int32)
        # Masked-completion convergence with a GLOBALLY uniform trip
        # count: members on different 'b' rows exit at different chunk
        # counts, but the loop body contains spatial collectives (halo
        # ppermutes + the psum'd residual), and replica groups running
        # different iteration counts deadlock the collective rendezvous
        # (observed as a hung CollectivePermute on the CPU backend). So
        # the loop runs until EVERY member everywhere is done — an
        # all-done flag reduced over 'b' rides in the carry, converged
        # members freeze via select (bitwise the individual trajectory,
        # exactly like the single-chip batched loops), and cond stays
        # collective-free.
        iv = max(1, min(interval, steps)) if steps else interval
        n_chunks = steps // iv if iv else 0
        remainder = steps - n_chunks * iv

        def step1(u):
            def one(ui, cxi, cyi):
                return sh.make_local_step(cfg, mesh, axes=axes,
                                          cxy=(cxi, cyi))(ui)
            return jax.vmap(one)(u, cx, cy)

        def residual(u_new, u_old):
            def one(a, b):
                return jax.lax.psum(residual_sq(a, b, accum), ("x", "y"))
            return jax.vmap(one)(u_new, u_old)

        def body(carry):
            u, i, chunks, done, _ = carry
            u_prev = chunk(u, cx, cy, iv - 1) if iv > 1 else u
            u_new = step1(u_prev)
            res = residual(u_new, u_prev)
            u = jnp.where(done[:, None, None], u, u_new)
            chunks = jnp.where(done, chunks, chunks + 1)
            done = done | (res < sensitivity)
            all_done = jax.lax.pmin(
                jnp.all(done).astype(jnp.int32), "b")
            return (u, i + 1, chunks, done, all_done)

        def cond(carry):
            _, i, _, _, all_done = carry
            return jnp.logical_and(i < n_chunks, all_done == 0)

        lb = u.shape[0]
        init = (u, jnp.asarray(0, jnp.int32),
                jnp.zeros((lb,), jnp.int32), jnp.zeros((lb,), bool),
                jnp.asarray(0, jnp.int32))
        u, _, chunks, done, _ = jax.lax.while_loop(cond, body, init)
        k = (chunks * iv).astype(jnp.int32)
        if remainder:
            u_adv = chunk(u, cx, cy, remainder)
            u = jnp.where(done[:, None, None], u, u_adv)
            k = jnp.where(done, k, k + remainder).astype(jnp.int32)
        return u, k

    mapped = shard_map_compat(
        local, mesh, in_specs=(P("b", "x", "y"), P("b"), P("b")),
        out_specs=(P("b", "x", "y"), P("b")), check_vma=False)
    # A stable name (the batch_runner convention): compile logs and
    # the recompile sentinel attribute spatial serve compiles to this
    # runner. Host-side metadata only.
    try:
        mapped.__name__ = "spatial_batch_runner"
    except (AttributeError, TypeError):
        pass
    u0 = jax.device_put(u0, NamedSharding(mesh, P("b", "x", "y")))
    bsh = NamedSharding(mesh, P("b"))
    cxs = jax.device_put(cxs, bsh)
    cys = jax.device_put(cys, bsh)
    meta = types.SimpleNamespace(mesh=mesh, nb=nb, pnx=pnx, pny=pny,
                                 spatial=spatial)
    return jax.jit(mapped), (u0, cxs, cys), b, meta


@functools.lru_cache(maxsize=64)
def spatial_batch_runner(nx: int, ny: int, steps: int, gridx: int,
                         gridy: int, convergence: bool = False,
                         interval: int = 20, sensitivity: float = 0.1,
                         halo: str = "fused", halo_depth=None,
                         n_devices=None):
    """The per-signature COMPILE-CACHED batch x spatial runner — the
    serve twin of ``batch_runner`` for members decomposed over a
    (gridx, gridy) submesh (the mesh-aware engine's spatial route,
    heat2d_tpu/mesh). The 3-axis program is built ONCE per signature
    (the jitted shard_map is shape-polymorphic over the batch axis —
    the capacity ladder's compile discipline is the caller's, exactly
    like the single-chip runner); each call pads the batch to a local-
    batch multiple with inert members, places the operands on the
    mesh, and crops on return. Returns ``run(u0, cxs, cys) -> (u, k)``
    with ``run.nb`` (members resident per launch wave) and ``run.meta``
    exposed for launch-record provenance."""
    spatial = gridx * gridy
    devices = list(jax.devices())
    if n_devices:
        devices = devices[:n_devices]
    nb = len(devices) // spatial
    if nb < 1:
        raise ValueError(
            f"spatial_batch_runner needs gridx*gridy = {spatial} "
            f"devices; have {len(devices)}")
    # The program is independent of the batch contents: build it from
    # a representative nb-member batch (the dummy placement is the one
    # build-time cost; launches reuse fn + meta forever).
    dummy_u = jnp.zeros((nb, nx, ny), jnp.float32)
    dummy_c = jnp.zeros((nb,), jnp.float32)
    fn, _args, _b, meta = _build_spatial(
        nx, ny, steps, gridx, gridy, dummy_u, dummy_c, dummy_c,
        devices, convergence, interval, sensitivity,
        halo_depth=halo_depth, halo=halo)
    from jax.sharding import NamedSharding, PartitionSpec as P

    gsh = NamedSharding(meta.mesh, P("b", "x", "y"))
    bsh = NamedSharding(meta.mesh, P("b"))

    def run(u0, cxs, cys):
        b = u0.shape[0]
        pad = (-b) % meta.nb
        if pad:       # inert members (cx=cy=0), cropped on return
            cxs = jnp.concatenate([cxs, jnp.zeros((pad,), cxs.dtype)])
            cys = jnp.concatenate([cys, jnp.zeros((pad,), cys.dtype)])
            u0 = jnp.concatenate(
                [u0, jnp.zeros((pad,) + u0.shape[1:], u0.dtype)],
                axis=0)
        if (meta.pnx, meta.pny) != (nx, ny):
            u0 = jnp.pad(u0, ((0, 0), (0, meta.pnx - nx),
                              (0, meta.pny - ny)))
        u, k = fn(jax.device_put(u0, gsh), jax.device_put(cxs, bsh),
                  jax.device_put(cys, bsh))
        return u[:b, :nx, :ny], k[:b]

    run.nb = meta.nb
    run.meta = meta
    run.jitted = fn
    return run


def run_ensemble_spatial(nx: int, ny: int, steps: int, cxs, cys,
                         gridx: int, gridy: int, u0=None, devices=None,
                         convergence: bool = False, interval: int = 20,
                         sensitivity: float = 0.1, halo_depth=None,
                         halo: str = "collective"):
    """Batch x spatial ensemble: returns (batch, steps_done), each
    member advanced on its own (gridx, gridy) spatial submesh. Bitwise
    identical per member to a dist2d run of the same (cx, cy) — the
    composition test pins this (``halo="fused"`` included: the overlap
    route is bitwise-equal to the collective one)."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    fn, args, b, _meta = _build_spatial(
        nx, ny, steps, gridx, gridy, u0, cxs, cys, devices,
        convergence, interval, sensitivity, halo_depth=halo_depth,
        halo=halo)
    u, k = fn(*args)
    return u[:b, :nx, :ny], k[:b]


def timed_ensemble(nx: int, ny: int, steps: int, cxs, cys, u0=None,
                   method: str = "auto", sharded: bool = False,
                   devices=None, convergence: bool = False,
                   interval: int = 20, sensitivity: float = 0.1,
                   spatial_grid=None, halo_depth=None,
                   halo: str = "collective", tap=None,
                   problem: str = "heat5"):
    """(batch, steps_done, elapsed): one ensemble launch under the
    reference timing protocol (compile/warmup excluded, scalar-readback
    fence) — the CLI entry point. ``sharded=True`` spreads members over
    a device-mesh batch axis; ``convergence=True`` runs the per-member
    early-exit schedule (steps_done is None on fixed-step runs, where
    every member runs exactly ``steps``). ``spatial_grid=(gridx,
    gridy)``: batch x spatial composition — each member spatially
    decomposed over a submesh (for members bigger than one device's
    HBM); implies the 3-axis mesh regardless of ``sharded``."""
    from heat2d_tpu.utils.timing import timed_call

    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    if problem != "heat5":
        from heat2d_tpu.config import ConfigError
        if sharded or spatial_grid is not None:
            raise ConfigError(
                f"problem {problem!r} runs the single-chip batch path "
                f"only (the sharded/spatial meshes are built for the "
                f"heat5 operator); drop sharded/spatial_grid")
        fn = batch_runner(nx, ny, steps, method,
                          convergence=convergence, interval=interval,
                          sensitivity=sensitivity, problem=problem)
        out, elapsed = timed_call(fn, u0, cxs, cys)
        if convergence:
            u, k = out
            return u, k, elapsed
        return out, None, elapsed
    if spatial_grid is not None:
        gx, gy = spatial_grid
        fn, args, b, _meta = _build_spatial(
            nx, ny, steps, gx, gy, u0, cxs, cys, devices,
            convergence, interval, sensitivity, halo_depth=halo_depth,
            halo=halo)
        (u, k), elapsed = timed_call(fn, *args)
        return (u[:b, :nx, :ny],
                k[:b] if convergence else None, elapsed)
    method = _pick_method(method, nx, ny)
    if convergence:
        # tap only on the single-process path: under a batch mesh each
        # device's callback would carry device-local member vectors
        # (indices no longer meaningful cluster-wide).
        local = _conv_runner(method, steps, interval, sensitivity,
                             tap=None if sharded else tap)
        if sharded:
            fn, args, b = _shard_local_fn(local, u0, cxs, cys, devices)
        else:
            fn, args, b = jax.jit(local), (u0, cxs, cys), cxs.shape[0]
        (u, k), elapsed = timed_call(fn, *args)
        if tap is not None and not sharded:
            _flush_taps()
        return u[:b], k[:b], elapsed
    if sharded:
        fn, args, b = _build_sharded(steps, method, u0, cxs, cys, devices)
    else:
        fn, args, b = _build_single(steps, method, u0, cxs, cys)
    out, elapsed = timed_call(fn, *args)
    return out[:b], None, elapsed


def ensemble_summary(batch, steps_done=None) -> dict:
    """Per-member residual-free diagnostics (max temp, total heat), plus
    per-member iteration counts on convergence runs."""
    batch = np.asarray(batch)
    out = {
        "members": int(batch.shape[0]),
        "max_temperature": [float(m) for m in batch.max(axis=(1, 2))],
        "total_heat": [float(s) for s in batch.sum(axis=(1, 2))],
    }
    if steps_done is not None:
        out["steps_done"] = [int(s) for s in steps_done]
    return out
