"""Ensemble runs — batch data-parallelism over problem instances.

The reference solves exactly one problem instance per launch (SURVEY.md
§2.3: "DP over batch / replicas — ABSENT"); parameter sweeps in Report.pdf
were separate compiles/runs per configuration. This module adds the
capability the survey flags as the natural TPU extension: ``vmap`` the
whole time loop over a batch of (cx, cy) diffusivity pairs (or a batch of
initial grids), so one compiled program advances every ensemble member in
lockstep — on one chip via vectorization, or sharded over a mesh axis with
the spatial modes unchanged.

This is how the reference's Table-4-style parameter studies collapse into
a single launch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from heat2d_tpu.models import engine
from heat2d_tpu.ops.init import inidat
from heat2d_tpu.ops.stencil import stencil_step


def run_ensemble(nx: int, ny: int, steps: int, cxs, cys, u0=None):
    """Advance an ensemble of diffusivity pairs ``steps`` steps.

    ``cxs``/``cys``: 1D arrays of equal length B. ``u0``: optional (B, nx,
    ny) batch of initial grids; defaults to B copies of the reference
    initial condition (mpi_heat2Dn.c:242-248). Returns (B, nx, ny).
    """
    cxs = jnp.asarray(cxs, jnp.float32)
    cys = jnp.asarray(cys, jnp.float32)
    if cxs.shape != cys.shape or cxs.ndim != 1:
        raise ValueError("cxs and cys must be equal-length 1D arrays")
    if u0 is None:
        u0 = jnp.broadcast_to(inidat(nx, ny), (cxs.shape[0], nx, ny))
    u0 = jnp.asarray(u0)
    if u0.shape != (cxs.shape[0], nx, ny):
        raise ValueError(
            f"u0 must be ({cxs.shape[0]}, {nx}, {ny}), got {u0.shape}")

    def solve_one(u, cx, cy):
        u, _ = engine.run_fixed(lambda v: stencil_step(v, cx, cy), u, steps)
        return u

    return jax.jit(jax.vmap(solve_one))(u0, cxs, cys)


def ensemble_summary(batch) -> dict:
    """Per-member residual-free diagnostics (max temp, total heat)."""
    batch = np.asarray(batch)
    return {
        "members": int(batch.shape[0]),
        "max_temperature": [float(m) for m in batch.max(axis=(1, 2))],
        "total_heat": [float(s) for s in batch.sum(axis=(1, 2))],
    }
