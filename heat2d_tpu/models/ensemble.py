"""Ensemble runs — batch data-parallelism over problem instances.

The reference solves exactly one problem instance per launch (SURVEY.md
§2.3: "DP over batch / replicas — ABSENT"); parameter sweeps in Report.pdf
were separate compiles/runs per configuration. This module adds the
capability the survey flags as the natural TPU extension, as a real mode
of the framework (CLI: ``--ensemble-cx/--ensemble-cy``):

- ``jnp`` method: ``vmap`` the whole time loop over the (cx, cy) batch —
  one compiled program advances every member in lockstep.
- ``pallas`` method: one kernel launch for the whole batch — the program
  grid walks members, each VMEM-resident, with its (cx, cy) pair riding
  as an SMEM scalar block (the diffusivities are traced per-member
  values, so they are kernel *operands* here, not the baked constants the
  single-instance kernels use).
- ``band`` method: HBM-sized members stream through the temporally-
  blocked band kernel (pallas_stencil kernel C) over a (member, band)
  program grid — 'auto' routes here when a member exceeds the VMEM
  budget, so big members get the same kernel class as mode='pallas'
  instead of a vmap fallback.
- ``run_ensemble_sharded``: the batch as a mesh axis — members shard
  across devices (`shard_map` over a 1D 'b' mesh, batch padded to a
  device multiple with inert members), each device advancing its members
  through the same single-chip paths. This is DP over replicas on ICI;
  each member must fit one device's HBM.
- ``run_ensemble_spatial``: batch x spatial composition for members
  BIGGER than one device — a ('b', 'x', 'y') mesh where each member is
  spatially decomposed over its own (gridx, gridy) submesh (the dist2d
  wide-halo scheme, vmapped over the device's local members).

This is how the reference's Table-4-style parameter studies collapse into
a single launch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from heat2d_tpu.models import engine
from heat2d_tpu.ops.init import inidat
from heat2d_tpu.ops.stencil import residual_sq, stencil_step


def _validated_batch(nx, ny, cxs, cys, u0):
    cxs = jnp.asarray(cxs, jnp.float32)
    cys = jnp.asarray(cys, jnp.float32)
    if cxs.shape != cys.shape or cxs.ndim != 1:
        raise ValueError("cxs and cys must be equal-length 1D arrays")
    if u0 is None:
        u0 = jnp.broadcast_to(inidat(nx, ny), (cxs.shape[0], nx, ny))
    u0 = jnp.asarray(u0)
    if u0.shape != (cxs.shape[0], nx, ny):
        raise ValueError(
            f"u0 must be ({cxs.shape[0]}, {nx}, {ny}), got {u0.shape}")
    return cxs, cys, u0


def _run_batch_jnp(u0, cxs, cys, *, steps):
    def solve_one(u, cx, cy):
        u, _ = engine.run_fixed(lambda v: stencil_step(v, cx, cy), u, steps)
        return u

    return jax.vmap(solve_one)(u0, cxs, cys)


def _ensemble_kernel(s_ref, u_ref, out_ref, *, steps):
    from heat2d_tpu.ops.pallas_stencil import _step_value
    cx = s_ref[0, 0, 0]
    cy = s_ref[0, 0, 1]
    u = u_ref[0]
    u = jax.lax.fori_loop(0, steps,
                          lambda _, v: _step_value(v, cx, cy), u,
                          unroll=False)
    out_ref[0] = u


def _run_batch_pallas(u0, cxs, cys, *, steps):
    """One pallas_call for the whole batch: program grid over members,
    each member's grid VMEM-resident for all ``steps`` (the
    multi_step_vmem design batched; members must individually pass
    fits_vmem — callers route)."""
    from jax.experimental import pallas as pl
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid)

    b, nx, ny = u0.shape
    # (B, 1, 2): a (1, 1, 2) block's last two dims equal the array's —
    # a (1, 2) block over (B, 2) violates the Mosaic block rule for
    # B > 1 (caught on real TPU only; interpret mode accepts it).
    scal = jnp.stack([cxs, cys], axis=1)[:, None, :]
    mspace, smem = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, 2), lambda i: (i, 0, 0), **smem),
            pl.BlockSpec((1, nx, ny), lambda i: (i, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((1, nx, ny), lambda i: (i, 0, 0), **mspace),
    )
    return pl.pallas_call(
        functools.partial(_ensemble_kernel, steps=steps),
        out_shape=jax.ShapeDtypeStruct(u0.shape, u0.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        **_parallel_grid(1))(scal, u0)


def _ensemble_band_kernel(s_ref, up_ref, u_ref, dn_ref, out_ref, *,
                          bm, tsteps, nx, ny):
    """Temporally-blocked band sweep with per-member (cx, cy) scalars —
    pallas_stencil._band_multi_kernel with the diffusivities as SMEM
    operands (traced per-member values) instead of baked constants, over
    a (member, band) program grid."""
    from heat2d_tpu.ops.pallas_stencil import _step_value, _unrolled_steps

    j = pl.program_id(1)
    cx = s_ref[0, 0, 0]
    cy = s_ref[0, 0, 1]
    ext = jnp.concatenate([up_ref[0, 0], u_ref[0], dn_ref[0, 0]], axis=0)
    gi = (j * bm - tsteps
          + jax.lax.broadcasted_iota(jnp.int32, (bm + 2 * tsteps, 1), 0))
    keep = (gi <= 0) | (gi >= nx - 1)
    out_ref[0] = _unrolled_steps(
        tsteps, lambda v: jnp.where(keep, v, _step_value(v, cx, cy)),
        ext)[tsteps:-tsteps]


def _batched_band_sweep(scal, u, bm, tsteps, nx, ny):
    """One T-step sweep of every member's bands: grid (B, nblk), member
    blocks aliased in place (each program reads only its own block; the
    neighbor-row strips ride as separate operands)."""
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid, _row_strips)

    b, m, n = u.shape
    nblk = m // bm
    t = tsteps
    zeros = jnp.zeros((b, 1, t, n), u.dtype)
    ups, dns = _row_strips(u.reshape(b, nblk, bm, n), t, zeros, zeros)
    mspace, smem = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, 2), lambda i, j: (i, 0, 0), **smem),
            pl.BlockSpec((1, 1, t, n), lambda i, j: (i, j, 0, 0), **mspace),
            pl.BlockSpec((1, bm, n), lambda i, j: (i, j, 0), **mspace),
            pl.BlockSpec((1, 1, t, n), lambda i, j: (i, j, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((1, bm, n), lambda i, j: (i, j, 0), **mspace),
    )
    return pl.pallas_call(
        functools.partial(_ensemble_band_kernel, bm=bm, tsteps=tsteps,
                          nx=nx, ny=ny),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        input_output_aliases={2: 0},
        **_parallel_grid(2))(scal, ups, u, dns)


def _run_batch_band(u0, cxs, cys, *, steps):
    """HBM-sized members: every member streamed through the temporally-
    blocked band kernel in one launch (the band_chunk design with the
    batch as a leading grid axis). Closes the VERDICT r2 weak-#3 gap
    where members too big for VMEM fell back to the vmap'd jnp path."""
    from heat2d_tpu.ops import pallas_stencil as ps

    b, nx, ny = u0.shape
    bm, m_pad = ps.plan_bands(nx, ny, u0.dtype)
    t = ps.DEFAULT_TSTEPS
    if bm <= 2 * t:
        t = max(1, (bm - 1) // 2)   # shallow bands: reduce sweep depth
    ps._check_band_vmem(bm, t, ny, u0.dtype)
    u = u0
    if m_pad > nx:
        u = jnp.pad(u, ((0, 0), (0, m_pad - nx), (0, 0)))
    scal = jnp.stack([cxs, cys], axis=1)[:, None, :]   # (B, 1, 2)
    nsweeps, rem = divmod(steps, t)
    if nsweeps:
        u = jax.lax.fori_loop(
            0, nsweeps,
            lambda _, v: _batched_band_sweep(scal, v, bm, t, nx, ny), u,
            unroll=False)
    if rem:
        u = _batched_band_sweep(scal, u, bm, rem, nx, ny)
    return u[:, :nx] if m_pad > nx else u


_BATCH_RUNNERS = {"jnp": _run_batch_jnp, "pallas": _run_batch_pallas,
                  "band": _run_batch_band}


# --------------------------------------------------------------------- #
# Convergence (early-exit) ensembles
# --------------------------------------------------------------------- #

def _run_batch_conv_jnp(u0, cxs, cys, *, steps, interval, sensitivity):
    """vmap of the engine convergence loop: JAX's while_loop batching
    rule gives masked completion for free — the combined loop runs while
    ANY member's predicate holds and select-freezes finished lanes, so
    each member's trajectory (and steps_done) is exactly its individual
    engine.run_convergence trajectory (the per-member bitwise-parity
    tests pin this)."""
    def solve_one(u, cx, cy):
        return engine.run_convergence(
            lambda v: stencil_step(v, cx, cy), residual_sq,
            u, steps, interval, sensitivity)

    return jax.vmap(solve_one)(u0, cxs, cys)


def _run_batch_conv_kernel(u0, cxs, cys, *, steps, interval, sensitivity,
                           runner):
    """Batched engine.run_convergence_chunked over the kernel runners:
    each chunk is ``interval-1`` fused steps plus one tracked step; the
    residual is per-member; converged members freeze (their stored plane
    stops updating) while the rest continue, and the loop exits when all
    members converge or the chunk budget is spent. The trailing
    ``steps % interval`` remainder runs unchecked on unconverged members
    only — the same schedule as the individual chunked loop, member-wise.
    """
    if steps:
        interval = max(1, min(interval, steps))
    n_chunks = steps // interval if interval else 0
    remainder = steps - n_chunks * interval
    b = u0.shape[0]

    def chunk(u, n):
        return runner(u, cxs, cys, steps=n)

    def body(carry):
        u, i, chunks, done = carry
        u_prev = chunk(u, interval - 1) if interval > 1 else u
        u_new = chunk(u_prev, 1)
        # vmap'd residual_sq so the per-member residual is the SAME
        # definition (cast order included) the individual loops use.
        res = jax.vmap(lambda a, b: residual_sq(a, b))(u_new, u_prev)
        # Members already done keep their frozen plane; the member that
        # converges THIS chunk stores u_new (matching the individual
        # loop, whose final plane is the one its residual was computed
        # from) and freezes starting next iteration.
        u = jnp.where(done[:, None, None], u, u_new)
        chunks = jnp.where(done, chunks, chunks + 1)
        done = done | (res < sensitivity)
        return (u, i + 1, chunks, done)

    def cond(carry):
        _, i, _, done = carry
        return jnp.logical_and(i < n_chunks,
                               jnp.logical_not(jnp.all(done)))

    init = (u0, jnp.asarray(0, jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool))
    u, _, chunks, done = jax.lax.while_loop(cond, body, init)
    k = (chunks * interval).astype(jnp.int32)
    if remainder:
        u_adv = chunk(u, remainder)
        u = jnp.where(done[:, None, None], u, u_adv)
        k = jnp.where(done, k, k + remainder).astype(jnp.int32)
    return u, k


def _conv_runner(method, steps, interval, sensitivity):
    """The jitted (u0, cxs, cys) -> (u, steps_done) convergence runner
    for a method — vmap'd engine loop for 'jnp', the batched chunked
    loop over the corresponding kernel runner otherwise."""
    if method == "jnp":
        return functools.partial(_run_batch_conv_jnp, steps=steps,
                                 interval=interval,
                                 sensitivity=sensitivity)
    return functools.partial(_run_batch_conv_kernel, steps=steps,
                             interval=interval, sensitivity=sensitivity,
                             runner=_BATCH_RUNNERS[method])


def run_ensemble_convergence(nx: int, ny: int, steps: int, interval: int,
                             sensitivity: float, cxs, cys, u0=None,
                             method: str = "auto"):
    """Ensemble with per-member convergence early-exit — the intended
    grad1612_mpi_heat.c:262-271 residual schedule applied member-wise
    (the reference could only run one instance per launch; SURVEY.md
    §2.3). Returns (batch, steps_done): converged members froze at
    their exit plane; ``steps_done[i]`` is member i's iteration count,
    a multiple of ``interval`` unless the step budget ran out first."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    method = _pick_method(method, nx, ny)
    fn = jax.jit(_conv_runner(method, steps, interval, sensitivity))
    return fn(u0, cxs, cys)


def _pick_method(method, nx, ny):
    if method != "auto":
        return method
    from heat2d_tpu.ops.pallas_stencil import fits_vmem
    return "pallas" if fits_vmem((nx, ny)) else "band"


def run_ensemble(nx: int, ny: int, steps: int, cxs, cys, u0=None,
                 method: str = "auto"):
    """Advance an ensemble of diffusivity pairs ``steps`` steps.

    ``cxs``/``cys``: 1D arrays of equal length B. ``u0``: optional (B, nx,
    ny) batch of initial grids; defaults to B copies of the reference
    initial condition (mpi_heat2Dn.c:242-248). Returns (B, nx, ny).

    ``method``: 'jnp' (vmap), 'pallas' (batched kernel, members must be
    VMEM-resident), 'band' (batched temporally-blocked band kernel for
    HBM-sized members), or 'auto' (pallas when a member fits VMEM, band
    otherwise).
    """
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    method = _pick_method(method, nx, ny)
    fn, args, b = _build_single(steps, method, u0, cxs, cys)
    return fn(*args)


def _build_single(steps, method, u0, cxs, cys):
    fn = jax.jit(functools.partial(_BATCH_RUNNERS[method], steps=steps))
    return fn, (u0, cxs, cys), cxs.shape[0]


def _shard_local_fn(local, u0, cxs, cys, devices):
    """Jitted shard_map program + placed inputs for a batch-axis mesh;
    pads the batch to a device multiple with inert members (cx=cy=0).
    ``local`` is any (u, cxs, cys) -> outputs batch function; each
    device runs it on its local members (device-local while_loops in the
    convergence case — no collective inside, so devices may exit their
    loops at different chunk counts)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from heat2d_tpu.parallel.mesh import shard_map_compat

    devices = list(devices if devices is not None else jax.devices())
    b, nx, ny = u0.shape
    nd = min(len(devices), b)
    devices = devices[:nd]
    pad = (-b) % nd
    if pad:
        cxs = jnp.concatenate([cxs, jnp.zeros((pad,), cxs.dtype)])
        cys = jnp.concatenate([cys, jnp.zeros((pad,), cys.dtype)])
        u0 = jnp.concatenate(
            [u0, jnp.zeros((pad, nx, ny), u0.dtype)], axis=0)

    mesh = Mesh(np.asarray(devices), ("b",))
    mapped = shard_map_compat(local, mesh, in_specs=P("b"),
                              out_specs=P("b"), check_vma=False)
    sharding = NamedSharding(mesh, P("b"))
    u0 = jax.device_put(u0, sharding)
    cxs = jax.device_put(cxs, sharding)
    cys = jax.device_put(cys, sharding)
    return jax.jit(mapped), (u0, cxs, cys), b


def _build_sharded(steps, method, u0, cxs, cys, devices):
    run = _BATCH_RUNNERS[method]

    def local(u, cx, cy):
        return run(u, cx, cy, steps=steps)

    return _shard_local_fn(local, u0, cxs, cys, devices)


def run_ensemble_sharded(nx: int, ny: int, steps: int, cxs, cys, u0=None,
                         method: str = "auto", devices=None):
    """Ensemble with the batch as a mesh axis: members shard over devices
    (DP over replicas — SURVEY.md §2.3), each device advancing its local
    members through the single-chip batch path. Returns (B, nx, ny)."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    method = _pick_method(method, nx, ny)
    fn, args, b = _build_sharded(steps, method, u0, cxs, cys, devices)
    return fn(*args)[:b]


def run_ensemble_convergence_sharded(nx: int, ny: int, steps: int,
                                     interval: int, sensitivity: float,
                                     cxs, cys, u0=None,
                                     method: str = "auto", devices=None):
    """Convergence ensemble with the batch as a mesh axis. Inert pad
    members (cx=cy=0) reach residual 0 after one chunk, so they converge
    immediately for any sensitivity > 0 and never hold their device's
    loop open (with sensitivity == 0 every member runs the full budget
    anyway). Returns (batch, steps_done), both cropped to B."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    method = _pick_method(method, nx, ny)
    local = _conv_runner(method, steps, interval, sensitivity)
    fn, args, b = _shard_local_fn(local, u0, cxs, cys, devices)
    u, k = fn(*args)
    return u[:b], k[:b]


# --------------------------------------------------------------------- #
# Batch x spatial composition: members bigger than one device's HBM
# --------------------------------------------------------------------- #

def _build_spatial(nx, ny, steps, gridx, gridy, u0, cxs, cys, devices,
                   convergence, interval, sensitivity, halo_depth=None):
    """Jitted runner + placed inputs for a 3-axis ('b', 'x', 'y') mesh:
    each member is spatially decomposed over a (gridx, gridy) submesh
    (the dist2d scheme — 4-neighbor wide-halo ppermute, VERDICT r3 weak
    #4's missing composition) while the batch shards over 'b'. Inside
    shard_map the member loop is a vmap over the device's local members,
    so the halo ppermutes and the per-member psum'd residual batch over
    the leading axis; per-member (cx, cy) ride as traced scalars through
    the jnp chunk path (sharded.make_local_chunk cxy=...). Convergence
    gives per-member early exit via the vmapped while_loop exactly as
    the single-chip batched loops do. Returns (fn, args, b)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.parallel import sharded as sh
    from heat2d_tpu.parallel.mesh import shard_map_compat

    b, _, _ = u0.shape
    devices = list(devices if devices is not None else jax.devices())
    spatial = gridx * gridy
    nb = len(devices) // spatial
    if nb < 1:
        raise ValueError(
            f"batch x spatial ensemble needs at least gridx*gridy = "
            f"{spatial} devices; have {len(devices)}")
    nb = min(nb, b)
    mesh = Mesh(np.asarray(devices[:nb * spatial]).reshape(
        nb, gridx, gridy), ("b", "x", "y"))
    axes = ("x", "y", gridx, gridy)

    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist2d",
                     gridx=gridx, gridy=gridy, convergence=convergence,
                     interval=interval, sensitivity=sensitivity,
                     halo_depth=halo_depth)
    pnx, pny = sh.padded_global_shape(cfg, mesh, axes)
    accum = jnp.float32

    pad = (-b) % nb
    if pad:       # inert members (cx=cy=0), cropped on return
        cxs = jnp.concatenate([cxs, jnp.zeros((pad,), cxs.dtype)])
        cys = jnp.concatenate([cys, jnp.zeros((pad,), cys.dtype)])
        u0 = jnp.concatenate(
            [u0, jnp.zeros((pad,) + u0.shape[1:], u0.dtype)], axis=0)
    if (pnx, pny) != (nx, ny):    # equal-shard spatial padding
        u0 = jnp.pad(u0, ((0, 0), (0, pnx - nx), (0, pny - ny)))

    def chunk(u, cx, cy, n):
        def one(ui, cxi, cyi):
            return sh.make_local_multi(cfg, mesh, axes=axes,
                                       cxy=(cxi, cyi))(ui, n)
        return jax.vmap(one)(u, cx, cy)

    def local(u, cx, cy):
        if not convergence:
            u = chunk(u, cx, cy, steps)
            return u, jnp.full(u.shape[:1], steps, jnp.int32)
        # Masked-completion convergence with a GLOBALLY uniform trip
        # count: members on different 'b' rows exit at different chunk
        # counts, but the loop body contains spatial collectives (halo
        # ppermutes + the psum'd residual), and replica groups running
        # different iteration counts deadlock the collective rendezvous
        # (observed as a hung CollectivePermute on the CPU backend). So
        # the loop runs until EVERY member everywhere is done — an
        # all-done flag reduced over 'b' rides in the carry, converged
        # members freeze via select (bitwise the individual trajectory,
        # exactly like the single-chip batched loops), and cond stays
        # collective-free.
        iv = max(1, min(interval, steps)) if steps else interval
        n_chunks = steps // iv if iv else 0
        remainder = steps - n_chunks * iv

        def step1(u):
            def one(ui, cxi, cyi):
                return sh.make_local_step(cfg, mesh, axes=axes,
                                          cxy=(cxi, cyi))(ui)
            return jax.vmap(one)(u, cx, cy)

        def residual(u_new, u_old):
            def one(a, b):
                return jax.lax.psum(residual_sq(a, b, accum), ("x", "y"))
            return jax.vmap(one)(u_new, u_old)

        def body(carry):
            u, i, chunks, done, _ = carry
            u_prev = chunk(u, cx, cy, iv - 1) if iv > 1 else u
            u_new = step1(u_prev)
            res = residual(u_new, u_prev)
            u = jnp.where(done[:, None, None], u, u_new)
            chunks = jnp.where(done, chunks, chunks + 1)
            done = done | (res < sensitivity)
            all_done = jax.lax.pmin(
                jnp.all(done).astype(jnp.int32), "b")
            return (u, i + 1, chunks, done, all_done)

        def cond(carry):
            _, i, _, _, all_done = carry
            return jnp.logical_and(i < n_chunks, all_done == 0)

        lb = u.shape[0]
        init = (u, jnp.asarray(0, jnp.int32),
                jnp.zeros((lb,), jnp.int32), jnp.zeros((lb,), bool),
                jnp.asarray(0, jnp.int32))
        u, _, chunks, done, _ = jax.lax.while_loop(cond, body, init)
        k = (chunks * iv).astype(jnp.int32)
        if remainder:
            u_adv = chunk(u, cx, cy, remainder)
            u = jnp.where(done[:, None, None], u, u_adv)
            k = jnp.where(done, k, k + remainder).astype(jnp.int32)
        return u, k

    mapped = shard_map_compat(
        local, mesh, in_specs=(P("b", "x", "y"), P("b"), P("b")),
        out_specs=(P("b", "x", "y"), P("b")), check_vma=False)
    u0 = jax.device_put(u0, NamedSharding(mesh, P("b", "x", "y")))
    bsh = NamedSharding(mesh, P("b"))
    cxs = jax.device_put(cxs, bsh)
    cys = jax.device_put(cys, bsh)
    return jax.jit(mapped), (u0, cxs, cys), b


def run_ensemble_spatial(nx: int, ny: int, steps: int, cxs, cys,
                         gridx: int, gridy: int, u0=None, devices=None,
                         convergence: bool = False, interval: int = 20,
                         sensitivity: float = 0.1, halo_depth=None):
    """Batch x spatial ensemble: returns (batch, steps_done), each
    member advanced on its own (gridx, gridy) spatial submesh. Bitwise
    identical per member to a dist2d run of the same (cx, cy) — the
    composition test pins this."""
    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    fn, args, b = _build_spatial(
        nx, ny, steps, gridx, gridy, u0, cxs, cys, devices,
        convergence, interval, sensitivity, halo_depth=halo_depth)
    u, k = fn(*args)
    return u[:b, :nx, :ny], k[:b]


def timed_ensemble(nx: int, ny: int, steps: int, cxs, cys, u0=None,
                   method: str = "auto", sharded: bool = False,
                   devices=None, convergence: bool = False,
                   interval: int = 20, sensitivity: float = 0.1,
                   spatial_grid=None, halo_depth=None):
    """(batch, steps_done, elapsed): one ensemble launch under the
    reference timing protocol (compile/warmup excluded, scalar-readback
    fence) — the CLI entry point. ``sharded=True`` spreads members over
    a device-mesh batch axis; ``convergence=True`` runs the per-member
    early-exit schedule (steps_done is None on fixed-step runs, where
    every member runs exactly ``steps``). ``spatial_grid=(gridx,
    gridy)``: batch x spatial composition — each member spatially
    decomposed over a submesh (for members bigger than one device's
    HBM); implies the 3-axis mesh regardless of ``sharded``."""
    from heat2d_tpu.utils.timing import timed_call

    cxs, cys, u0 = _validated_batch(nx, ny, cxs, cys, u0)
    if spatial_grid is not None:
        gx, gy = spatial_grid
        fn, args, b = _build_spatial(
            nx, ny, steps, gx, gy, u0, cxs, cys, devices,
            convergence, interval, sensitivity, halo_depth=halo_depth)
        (u, k), elapsed = timed_call(fn, *args)
        return (u[:b, :nx, :ny],
                k[:b] if convergence else None, elapsed)
    method = _pick_method(method, nx, ny)
    if convergence:
        local = _conv_runner(method, steps, interval, sensitivity)
        if sharded:
            fn, args, b = _shard_local_fn(local, u0, cxs, cys, devices)
        else:
            fn, args, b = jax.jit(local), (u0, cxs, cys), cxs.shape[0]
        (u, k), elapsed = timed_call(fn, *args)
        return u[:b], k[:b], elapsed
    if sharded:
        fn, args, b = _build_sharded(steps, method, u0, cxs, cys, devices)
    else:
        fn, args, b = _build_single(steps, method, u0, cxs, cys)
    out, elapsed = timed_call(fn, *args)
    return out[:b], None, elapsed


def ensemble_summary(batch, steps_done=None) -> dict:
    """Per-member residual-free diagnostics (max temp, total heat), plus
    per-member iteration counts on convergence runs."""
    batch = np.asarray(batch)
    out = {
        "members": int(batch.shape[0]),
        "max_temperature": [float(m) for m in batch.max(axis=(1, 2))],
        "total_heat": [float(s) for s in batch.sum(axis=(1, 2))],
    }
    if steps_done is not None:
        out["steps_done"] = [int(s) for s in steps_done]
    return out
