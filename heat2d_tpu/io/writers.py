"""Text grid writers — byte-compatible with the reference's ``.dat`` files.

The reference has two *different* text layouts for the same physics
(SURVEY.md A.6), and parity requires both:

- **baseline** (mpi_heat2Dn.c:253-268, ``prtdat``): lines iterate the y
  index *descending*, each line sweeps x ascending; values ``%6.1f``,
  single space *between* values, newline at line end (no trailing space).
  This is a transposed/flipped view of the grid.
- **rowmajor** (grad1612_mpi_heat.c:191-203, 286-298): global row-major
  i-then-j order; every value formatted ``"%6.1f "`` (trailing space on
  every value, including the last), newline per row.

Formatting parity: C's ``%6.1f`` of a float promoted to double and Python's
``format(float(v), '6.1f')`` produce identical bytes (both do
correctly-rounded decimal conversion of the same binary64 value, including
``  -0.0``). A native C++ formatter (heat2d_tpu/native) accelerates large
grids; this module transparently uses it when built.
"""

from __future__ import annotations

import numpy as np


def _as_host_f32(u) -> np.ndarray:
    a = np.asarray(u, dtype=np.float32)
    if a.ndim != 2:
        raise ValueError(f"expected a 2D grid, got shape {a.shape}")
    return a


_NATIVE = None
_NATIVE_PROBED = False


def _native():
    global _NATIVE, _NATIVE_PROBED
    if not _NATIVE_PROBED:
        _NATIVE_PROBED = True
        try:
            from heat2d_tpu.native import lib as native_lib
            _NATIVE = native_lib.load()
        except Exception:
            _NATIVE = None
    return _NATIVE


def format_grid_baseline(u) -> str:
    """mpi_heat2Dn.c prtdat byte format (y-descending lines, x across)."""
    a = _as_host_f32(u)
    nat = _native()
    if nat is not None:
        return nat.format_baseline(a)
    nx, ny = a.shape
    lines = []
    for iy in range(ny - 1, -1, -1):
        lines.append(" ".join(format(float(a[ix, iy]), "6.1f")
                              for ix in range(nx)))
    return "\n".join(lines) + "\n"


def format_grid_rowmajor(u) -> str:
    """grad1612 writer byte format (row-major, trailing space per value)."""
    a = _as_host_f32(u)
    nat = _native()
    if nat is not None:
        return nat.format_rowmajor(a)
    rows = []
    for i in range(a.shape[0]):
        rows.append("".join(format(float(v), "6.1f") + " " for v in a[i]))
    return "\n".join(rows) + "\n"


def write_grid_baseline(u, path) -> None:
    from heat2d_tpu.io.binary import write_text_atomic
    write_text_atomic(format_grid_baseline(u), path)


def write_grid_rowmajor(u, path) -> None:
    from heat2d_tpu.io.binary import write_text_atomic
    write_text_atomic(format_grid_rowmajor(u), path)


def read_grid_text(path, layout: str = "rowmajor") -> np.ndarray:
    """Parse either .dat layout back into a row-major (nx, ny) float32 grid."""
    with open(path) as f:
        rows = [[float(tok) for tok in line.split()]
                for line in f if line.strip()]
    a = np.asarray(rows, dtype=np.float32)
    if layout == "rowmajor":
        return a
    if layout == "baseline":
        # File lines are iy = ny-1..0, columns ix = 0..nx-1.
        return a[::-1].T.copy()
    raise ValueError(f"unknown layout {layout!r}")
