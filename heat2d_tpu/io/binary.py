"""Binary state dumps and checkpoint/resume.

Format parity: the reference's MPI-IO collective writes
(grad1612_mpi_heat.c:178-190, 283-285) produce the full global grid as raw
native-endian float32 in global row-major order — a checkpoint format
without a loader (SURVEY.md §5.4). We keep the byte format identical
(``read_binary`` can load the reference's ``*_binary.dat`` files) and add
the missing loader plus a JSON sidecar (step counter + config) so the dump
doubles as a restart point.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np


class CheckpointCorruptError(ValueError):
    """A checkpoint failed its integrity checks (sha256 digest mismatch,
    truncated binary, unreadable sidecar) — a torn write, not a usable
    restart point. ``resil.CheckpointManager.latest_valid`` catches this
    and falls back to the previous snapshot."""


def _sha256_file(path, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _fsync_path(path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_binary(u, path) -> None:
    """Raw f32 row-major dump — byte-identical to the MPI-IO file layout."""
    a = np.asarray(u, dtype=np.float32)
    a.tofile(path)


def write_binary_sharded(u, path, shape=None) -> None:
    """Per-shard parallel write of a (possibly host-spanning) jax.Array —
    the MPI_File_write_all analogue (grad1612_mpi_heat.c:182-189, subarray
    datatype + collective write): every process writes its addressable
    shards into the one global row-major f32 file at their global offsets.
    No process ever materializes the full grid.

    COLLECTIVE: every process must call it (process 0 pre-sizes the file;
    barriers bracket the writes so the file is complete on return —
    like MPI-IO, a shared filesystem is assumed across hosts).

    ``shape``: true domain (nx, ny) — shard cells past it (the equal-shard
    padding of uneven decompositions) are cropped, so the file layout is
    the reference's exactly.
    """
    import jax

    nx, ny = shape if shape is not None else u.shape
    multi = jax.process_count() > 1
    if multi:
        from jax.experimental import multihost_utils
    if jax.process_index() == 0:
        with open(path, "wb") as f:
            f.truncate(nx * ny * 4)
    if multi:
        multihost_utils.sync_global_devices(f"binary_sharded:create:{path}")
    mm = np.memmap(path, dtype=np.float32, mode="r+", shape=(nx, ny))
    try:
        for sh in u.addressable_shards:
            if sh.replica_id != 0:
                continue
            rs, cs = sh.index
            r0, c0 = rs.start or 0, cs.start or 0
            if r0 >= nx or c0 >= ny:
                continue          # shard lies wholly in the padding
            blk = np.asarray(sh.data, dtype=np.float32)
            r1 = min(r0 + blk.shape[0], nx)
            c1 = min(c0 + blk.shape[1], ny)
            mm[r0:r1, c0:c1] = blk[:r1 - r0, :c1 - c0]
        mm.flush()
    finally:
        del mm
    if multi:
        multihost_utils.sync_global_devices(f"binary_sharded:done:{path}")


def read_binary(path, shape) -> np.ndarray:
    a = np.fromfile(path, dtype=np.float32)
    expected = int(np.prod(shape))
    if a.size != expected:
        raise ValueError(
            f"{path}: expected {expected} float32 values for shape {shape}, "
            f"found {a.size}")
    return a.reshape(shape)


def write_text_atomic(text: str, path) -> None:
    """Commit a text artifact crash-consistently: staged to
    ``path + '.tmp'``, fsync'd, promoted with ``os.replace`` — the
    checkpoint protocol's discipline for every persistent text file
    (run records, metric exports, grid dumps). A reader can never see
    a half-written artifact; a crash leaves the previous version (or
    nothing plus a ``.tmp``), never a torn file. Enforced tree-wide by
    lint rule R001 (docs/ANALYSIS.md)."""
    path = str(path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_json_atomic(obj, path, **dump_kwargs) -> None:
    """``write_text_atomic`` for one JSON document (run records,
    exported dbs, scaling records)."""
    dump_kwargs.setdefault("indent", 2)
    write_text_atomic(json.dumps(obj, **dump_kwargs) + "\n", path)


def checkpoint_tmp_path(path) -> str:
    """The staging file a checkpoint is written to before its atomic
    commit. Deterministic (not per-pid): on the multihost shared-FS path
    every rank must target the ONE staging file."""
    return str(path) + ".tmp"


def commit_checkpoint_files(tmp_path, path, step: int, config,
                            out_shape) -> None:
    """Atomically promote a fully-written staging binary to a durable
    checkpoint: digest -> fsync -> ``os.replace`` the binary -> atomic
    sidecar with the digest. Crash windows (exercised by resil/chaos.py):

    - before the binary replace: only ``tmp_path`` exists — the previous
      checkpoint pair is untouched and still loads;
    - between the two replaces: the NEW binary sits beside the OLD (or a
      missing) sidecar, whose ``sha256`` no longer matches — a torn pair
      ``load_checkpoint`` rejects as ``CheckpointCorruptError``;
    - after the sidecar replace: the new checkpoint is complete.
    """
    from heat2d_tpu.resil import chaos
    chaos.checkpoint_point("mid_write")
    digest = _sha256_file(tmp_path)
    _fsync_path(tmp_path)
    os.replace(tmp_path, path)
    chaos.checkpoint_point("pre_meta")
    meta = {
        "step": int(step),
        "shape": [int(s) for s in out_shape],
        "dtype": "float32",
        "sha256": digest,
        "config": config.to_dict() if hasattr(config, "to_dict")
                  else dict(config or {}),
        "format": "heat2d-tpu-checkpoint-v1",
    }
    meta_path = str(path) + ".meta.json"
    meta_tmp = meta_path + ".tmp"
    with open(meta_tmp, "w") as f:
        json.dump(meta, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(meta_tmp, meta_path)
    # fsync the directory: os.replace orders the renames but does not
    # make them durable — power loss could otherwise drop both entries
    # even after the caller was told the checkpoint committed.
    _fsync_path(os.path.dirname(os.path.abspath(str(path))))


#: dtypes an auxiliary field file may carry. Bools (observation masks)
#: store as uint8 bytes with dtype "bool" in the sidecar — raw files
#: stay dtype-pure and the loader restores the bool view.
FIELD_DTYPES = ("float32", "float64", "int32", "uint8", "bool")

FIELD_FORMAT = "heat2d-tpu-field-v1"


def save_field(a, path, name: str = "field", extra=None) -> None:
    """Auxiliary parameter field (diffusivity grid, observation mask,
    recovered inverse solution) as a raw binary + digest sidecar —
    the checkpoint protocol generalized past the float32 state grid:
    staged to ``path + '.tmp'``, digested, atomically promoted, then
    the ``.meta.json`` sidecar (shape, dtype, sha256, ``name``, any
    ``extra`` keys) replaces the same way. ``load_field`` verifies the
    digest, so a torn copy can never load as a valid field.
    """
    a = np.asarray(a)
    dtype = "bool" if a.dtype == np.bool_ else str(a.dtype)
    if dtype not in FIELD_DTYPES:
        raise ValueError(
            f"field dtype must be one of {FIELD_DTYPES}, got {a.dtype}")
    raw = a.astype(np.uint8) if dtype == "bool" else a
    tmp = checkpoint_tmp_path(path)
    raw.tofile(tmp)
    digest = _sha256_file(tmp)
    _fsync_path(tmp)
    os.replace(tmp, path)
    meta = {
        "format": FIELD_FORMAT,
        "name": str(name),
        "shape": [int(s) for s in a.shape],
        "dtype": dtype,
        "sha256": digest,
        **(dict(extra) if extra else {}),
    }
    meta_path = str(path) + ".meta.json"
    meta_tmp = meta_path + ".tmp"
    with open(meta_tmp, "w") as f:
        json.dump(meta, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(meta_tmp, meta_path)
    _fsync_path(os.path.dirname(os.path.abspath(str(path))))


def load_field(path, verify: bool = True):
    """Load an auxiliary field saved by ``save_field``. Returns
    ``(array, meta)``; digest mismatch, truncation, or an unreadable
    sidecar raise ``CheckpointCorruptError`` (``verify=False`` skips
    the digest check)."""
    meta_path = str(path) + ".meta.json"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
        shape = tuple(int(s) for s in meta["shape"])
        dtype = str(meta["dtype"])
        digest = meta.get("sha256")
    except (OSError, json.JSONDecodeError, KeyError, ValueError,
            TypeError) as e:
        raise CheckpointCorruptError(f"{path}: {e}") from e
    if dtype not in FIELD_DTYPES:
        raise CheckpointCorruptError(
            f"{path}: sidecar dtype {dtype!r} not in {FIELD_DTYPES}")
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except OSError as e:
        raise CheckpointCorruptError(f"{path}: {e}") from e
    if verify and digest is not None:
        actual = hashlib.sha256(buf).hexdigest()
        if actual != digest:
            raise CheckpointCorruptError(
                f"{path}: sha256 mismatch (sidecar {digest[:12]}…, file "
                f"{actual[:12]}…) — torn or corrupt field file")
    raw_dtype = np.uint8 if dtype == "bool" else np.dtype(dtype)
    a = np.frombuffer(buf, dtype=raw_dtype)
    expected = int(np.prod(shape)) if shape else 1
    if a.size != expected:
        raise CheckpointCorruptError(
            f"{path}: expected {expected} {dtype} values for shape "
            f"{shape}, found {a.size}")
    a = a.reshape(shape).copy()
    if dtype == "bool":
        a = a.astype(np.bool_)
    return a, meta


def save_checkpoint(u, step: int, config, path, shape=None) -> None:
    """State dump + sidecar, committed CRASH-CONSISTENTLY: the binary is
    staged to ``path + '.tmp'`` and promoted with ``os.replace``, then
    the sidecar (``path + '.meta.json'``, carrying the binary's sha256)
    is replaced the same way — at every instant the pair on disk either
    loads verified or is detectably torn, never silently half-new
    (``commit_checkpoint_files`` documents the crash windows).

    Host arrays write locally (call on one rank). A host-spanning
    jax.Array writes via write_binary_sharded — then the call is
    COLLECTIVE (all processes): every rank stages into the one shared
    temp file, and rank 0 commits after the collective write's closing
    barrier; pass ``shape`` to crop equal-shard padding.
    """
    collective = not getattr(u, "is_fully_addressable", True)
    tmp = checkpoint_tmp_path(path)
    if collective:
        write_binary_sharded(u, tmp, shape=shape)
        import jax
        primary = jax.process_index() == 0
        out_shape = shape if shape is not None else u.shape
    else:
        primary = True
        u = np.asarray(u)
        if shape is not None and tuple(u.shape) != tuple(shape):
            u = u[:shape[0], :shape[1]]
        write_binary(u, tmp)
        out_shape = u.shape
    if primary:
        commit_checkpoint_files(tmp, path, step, config, out_shape)
    if collective:
        import jax
        if jax.process_count() > 1:
            # No rank may return before the commit is complete: a driver
            # that proceeds on a non-zero rank (e.g. immediately resumes)
            # must not race a missing/stale pair.
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"checkpoint:meta:{path}")


def load_checkpoint(path, shape=None, verify: bool = True):
    """Returns (grid, step, config_dict). If no sidecar exists (e.g. a raw
    reference ``final_binary.dat``), ``shape`` is required and step=0.

    When the sidecar carries a ``sha256`` digest (every checkpoint since
    the atomic-commit format) the binary is verified against it;
    mismatch, truncation, or an unreadable sidecar raise
    ``CheckpointCorruptError`` — a torn pair must not load as if intact.
    ``verify=False`` skips the digest check (debugging torn files).
    """
    meta_path = str(path) + ".meta.json"
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            meta_shape = tuple(meta["shape"])
            step = int(meta["step"])
            digest = meta.get("sha256")
        except (json.JSONDecodeError, KeyError, ValueError,
                TypeError) as e:
            raise CheckpointCorruptError(f"{path}: {e}") from e
        # One disk read serves both the digest check and the grid:
        # latest_valid() walks manifest entries with this, so a resume
        # never pays double I/O per snapshot tried.
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except OSError as e:
            raise CheckpointCorruptError(f"{path}: {e}") from e
        if verify and digest is not None:
            actual = hashlib.sha256(buf).hexdigest()
            if actual != digest:
                raise CheckpointCorruptError(
                    f"{path}: sha256 mismatch (sidecar {digest[:12]}…, "
                    f"file {actual[:12]}…) — torn or corrupt checkpoint")
        a = np.frombuffer(buf, dtype=np.float32)
        expected = int(np.prod(meta_shape))
        if a.size != expected:
            raise CheckpointCorruptError(
                f"{path}: expected {expected} float32 values for shape "
                f"{meta_shape}, found {a.size}")
        # .copy(): frombuffer is read-only; callers get a writable grid
        # exactly as np.fromfile used to hand them.
        return a.reshape(meta_shape).copy(), step, meta.get("config", {})
    if shape is None:
        raise ValueError(f"no sidecar at {meta_path}; pass shape= explicitly")
    return read_binary(path, shape), 0, {}
