"""Binary state dumps and checkpoint/resume.

Format parity: the reference's MPI-IO collective writes
(grad1612_mpi_heat.c:178-190, 283-285) produce the full global grid as raw
native-endian float32 in global row-major order — a checkpoint format
without a loader (SURVEY.md §5.4). We keep the byte format identical
(``read_binary`` can load the reference's ``*_binary.dat`` files) and add
the missing loader plus a JSON sidecar (step counter + config) so the dump
doubles as a restart point.
"""

from __future__ import annotations

import json
import os

import numpy as np


def write_binary(u, path) -> None:
    """Raw f32 row-major dump — byte-identical to the MPI-IO file layout."""
    a = np.asarray(u, dtype=np.float32)
    a.tofile(path)


def read_binary(path, shape) -> np.ndarray:
    a = np.fromfile(path, dtype=np.float32)
    expected = int(np.prod(shape))
    if a.size != expected:
        raise ValueError(
            f"{path}: expected {expected} float32 values for shape {shape}, "
            f"found {a.size}")
    return a.reshape(shape)


def save_checkpoint(u, step: int, config, path) -> None:
    """State dump + sidecar. ``path`` is the binary file; sidecar is
    ``path + '.meta.json'``."""
    write_binary(u, path)
    meta = {
        "step": int(step),
        "shape": [int(s) for s in np.asarray(u).shape],
        "dtype": "float32",
        "config": config.to_dict() if hasattr(config, "to_dict") else dict(config or {}),
        "format": "heat2d-tpu-checkpoint-v1",
    }
    with open(str(path) + ".meta.json", "w") as f:
        json.dump(meta, f, indent=2)


def load_checkpoint(path, shape=None):
    """Returns (grid, step, config_dict). If no sidecar exists (e.g. a raw
    reference ``final_binary.dat``), ``shape`` is required and step=0."""
    meta_path = str(path) + ".meta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        grid = read_binary(path, tuple(meta["shape"]))
        return grid, int(meta["step"]), meta.get("config", {})
    if shape is None:
        raise ValueError(f"no sidecar at {meta_path}; pass shape= explicitly")
    return read_binary(path, shape), 0, {}
