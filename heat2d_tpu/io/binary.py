"""Binary state dumps and checkpoint/resume.

Format parity: the reference's MPI-IO collective writes
(grad1612_mpi_heat.c:178-190, 283-285) produce the full global grid as raw
native-endian float32 in global row-major order — a checkpoint format
without a loader (SURVEY.md §5.4). We keep the byte format identical
(``read_binary`` can load the reference's ``*_binary.dat`` files) and add
the missing loader plus a JSON sidecar (step counter + config) so the dump
doubles as a restart point.
"""

from __future__ import annotations

import json
import os

import numpy as np


def write_binary(u, path) -> None:
    """Raw f32 row-major dump — byte-identical to the MPI-IO file layout."""
    a = np.asarray(u, dtype=np.float32)
    a.tofile(path)


def write_binary_sharded(u, path, shape=None) -> None:
    """Per-shard parallel write of a (possibly host-spanning) jax.Array —
    the MPI_File_write_all analogue (grad1612_mpi_heat.c:182-189, subarray
    datatype + collective write): every process writes its addressable
    shards into the one global row-major f32 file at their global offsets.
    No process ever materializes the full grid.

    COLLECTIVE: every process must call it (process 0 pre-sizes the file;
    barriers bracket the writes so the file is complete on return —
    like MPI-IO, a shared filesystem is assumed across hosts).

    ``shape``: true domain (nx, ny) — shard cells past it (the equal-shard
    padding of uneven decompositions) are cropped, so the file layout is
    the reference's exactly.
    """
    import jax

    nx, ny = shape if shape is not None else u.shape
    multi = jax.process_count() > 1
    if multi:
        from jax.experimental import multihost_utils
    if jax.process_index() == 0:
        with open(path, "wb") as f:
            f.truncate(nx * ny * 4)
    if multi:
        multihost_utils.sync_global_devices(f"binary_sharded:create:{path}")
    mm = np.memmap(path, dtype=np.float32, mode="r+", shape=(nx, ny))
    try:
        for sh in u.addressable_shards:
            if sh.replica_id != 0:
                continue
            rs, cs = sh.index
            r0, c0 = rs.start or 0, cs.start or 0
            if r0 >= nx or c0 >= ny:
                continue          # shard lies wholly in the padding
            blk = np.asarray(sh.data, dtype=np.float32)
            r1 = min(r0 + blk.shape[0], nx)
            c1 = min(c0 + blk.shape[1], ny)
            mm[r0:r1, c0:c1] = blk[:r1 - r0, :c1 - c0]
        mm.flush()
    finally:
        del mm
    if multi:
        multihost_utils.sync_global_devices(f"binary_sharded:done:{path}")


def read_binary(path, shape) -> np.ndarray:
    a = np.fromfile(path, dtype=np.float32)
    expected = int(np.prod(shape))
    if a.size != expected:
        raise ValueError(
            f"{path}: expected {expected} float32 values for shape {shape}, "
            f"found {a.size}")
    return a.reshape(shape)


def save_checkpoint(u, step: int, config, path, shape=None) -> None:
    """State dump + sidecar. ``path`` is the binary file; sidecar is
    ``path + '.meta.json'``.

    Host arrays write locally (call on one rank). A host-spanning
    jax.Array writes via write_binary_sharded — then the call is
    COLLECTIVE (all processes) and rank 0 writes the sidecar; pass
    ``shape`` to crop equal-shard padding.
    """
    collective = not getattr(u, "is_fully_addressable", True)
    if collective:
        write_binary_sharded(u, path, shape=shape)
        import jax
        primary = jax.process_index() == 0
        out_shape = shape if shape is not None else u.shape
    else:
        primary = True
        u = np.asarray(u)
        if shape is not None and tuple(u.shape) != tuple(shape):
            u = u[:shape[0], :shape[1]]
        write_binary(u, path)
        out_shape = u.shape
    if primary:
        meta = {
            "step": int(step),
            "shape": [int(s) for s in out_shape],
            "dtype": "float32",
            "config": config.to_dict() if hasattr(config, "to_dict") else dict(config or {}),
            "format": "heat2d-tpu-checkpoint-v1",
        }
        with open(str(path) + ".meta.json", "w") as f:
            json.dump(meta, f, indent=2)
    if collective:
        import jax
        if jax.process_count() > 1:
            # No rank may return before the sidecar exists: a driver that
            # proceeds on a non-zero rank (e.g. immediately resumes) must
            # not race a missing/stale sidecar.
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"checkpoint:meta:{path}")


def load_checkpoint(path, shape=None):
    """Returns (grid, step, config_dict). If no sidecar exists (e.g. a raw
    reference ``final_binary.dat``), ``shape`` is required and step=0."""
    meta_path = str(path) + ".meta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        grid = read_binary(path, tuple(meta["shape"]))
        return grid, int(meta["step"]), meta.get("config", {})
    if shape is None:
        raise ValueError(f"no sidecar at {meta_path}; pass shape= explicitly")
    return read_binary(path, shape), 0, {}
