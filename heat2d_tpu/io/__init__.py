from heat2d_tpu.io.writers import (
    format_grid_baseline,
    format_grid_rowmajor,
    write_grid_baseline,
    write_grid_rowmajor,
    read_grid_text,
)
from heat2d_tpu.io.binary import (
    CheckpointCorruptError,
    checkpoint_tmp_path,
    commit_checkpoint_files,
    write_binary,
    write_binary_sharded,
    read_binary,
    save_checkpoint,
    load_checkpoint,
    save_field,
    load_field,
)

__all__ = [
    "format_grid_baseline",
    "format_grid_rowmajor",
    "write_grid_baseline",
    "write_grid_rowmajor",
    "read_grid_text",
    "CheckpointCorruptError",
    "checkpoint_tmp_path",
    "commit_checkpoint_files",
    "write_binary",
    "write_binary_sharded",
    "read_binary",
    "save_checkpoint",
    "load_checkpoint",
    "save_field",
    "load_field",
]
