"""Solve-serving subsystem — the inference-stack front half over the
batched ensemble engine (ROADMAP north star: admit heavy concurrent
traffic and amortize it onto the hardware).

The reference (and the repo until this package) could only run one-shot
CLI/bench launches. This package adds the serving trio that turns the
ensemble layer's one-launch-many-members capability into a service:

- ``schema``  — ``SolveRequest``/``SolveResult`` with a canonical
                content hash (cache/dedup key) and a compiled signature
                (batching key), plus structured ``Rejected`` errors.
- ``cache``   — bounded content-addressed LRU result cache +
                single-flight in-flight deduplication.
- ``batcher`` — async admission queue, shape-bucketed micro-batching
                (``max_delay``/``max_batch``), queue-depth load
                shedding, per-request timeouts.
- ``engine``  — bucket -> ONE ``run_ensemble`` launch through the
                per-signature compile cache (models/ensemble.
                batch_runner): warm signatures never retrace; batch
                shapes pad to power-of-two capacities so each signature
                compiles O(log max_batch) programs total.
- ``server``  — ``SolveServer`` composing the above + the synchronous
                ``Client``; every stage exports counters/gauges/
                histograms through obs/metrics (docs/SERVING.md).
- ``cli``     — ``heat2d-tpu-serve`` (``--selftest`` smoke +
                ``--requests`` file serving).
"""

from heat2d_tpu.serve.schema import Rejected, SolveRequest, SolveResult
from heat2d_tpu.serve.server import Client, SolveServer

__all__ = ["Rejected", "SolveRequest", "SolveResult", "Client",
           "SolveServer"]
