"""The solve server: admission -> cache -> single-flight -> micro-batch
-> ensemble launch, instrumented end to end.

Request lifecycle (``SolveServer.submit``):

1. **Validate** — malformed specs get ``Rejected("invalid")`` before
   touching any shared state.
2. **Cache** — a content-hash hit returns a completed future
   immediately (the stored grid is the cold solve's output, bitwise).
3. **Single-flight** — an identical request already in flight attaches
   to the leader's future (one compute, N answers).
4. **Queue** — the leader enters the micro-batcher's signature bucket;
   over-depth load is shed at the door, queued requests can time out.
5. **Launch** — the scheduler thread dispatches the bucket as one
   ensemble launch through the per-signature compile cache; results
   fill the cache, resolve futures, and record latency.

``submit`` returns a ``concurrent.futures.Future[SolveResult]`` and
never raises (rejections arrive AS the future's exception, uniformly,
so async callers have one error path). ``Client`` is the synchronous
wrapper tests and the CLI use.

Resilience (resil/ subsystem): a dispatched launch runs under the
retry policy (transient failures — injected ``ChaosError``, runtime/IO
errors — back off and retry instead of surfacing as terminal errors)
and a deadline ``Watchdog`` (a wedged launch fails its waiters with
``Rejected("watchdog_timeout")`` instead of hanging them). Repeated
dispatch failures trip ``DegradedMode``: fresh uncached work is shed at
admission with ``Rejected("degraded")`` while cache hits keep being
served — partial availability under a sick backend.

Metrics: ``serve_requests_total{outcome}`` counter and the
``serve_e2e_latency_s`` histogram here, plus ``serve_retries_total``,
``serve_watchdog_timeouts_total``, ``serve_degraded{,_shed_total}``,
``serve_breaker_trips_total`` and everything the cache / batcher /
engine layers record (docs/SERVING.md + docs/RESILIENCE.md tables).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

from heat2d_tpu.obs import tracing
from heat2d_tpu.resil.retry import (DegradedMode, RetryPolicy, Watchdog,
                                    call_with_retries)
from heat2d_tpu.serve.batcher import MicroBatcher
from heat2d_tpu.serve.cache import ResultCache, SingleFlight
from heat2d_tpu.serve.engine import EnsembleEngine
from heat2d_tpu.serve.schema import (Rejected, SolveRequest, SolveResult,
                                     attach_trace, request_trace)


class SolveServer:
    """In-process serving front end over the batched ensemble engine."""

    def __init__(self, *, max_batch: int = 8, max_delay: float = 0.005,
                 max_queue: int = 256, cache_size: int = 256,
                 default_timeout: Optional[float] = 30.0,
                 registry=None, retry_policy: Optional[RetryPolicy] = None,
                 launch_deadline: Optional[float] = None,
                 breaker: Optional[DegradedMode] = None,
                 deadline_clock=None, engine=None, admission=None):
        """``engine``: the solve executor — default a single-chip
        ``EnsembleEngine``; pass a ``mesh.MeshEnsembleEngine`` to
        serve over the whole device mesh (its own ``max_batch``, a
        device multiple, then drives the batcher so buckets fill the
        mesh). ``admission``: optional modeled-capacity admission
        control (``mesh.MeshAdmission``) — leaders it refuses are shed
        with the structured rejection it returns BEFORE queueing,
        beside (not instead of) the breaker and queue-depth checks;
        cache hits and coalesced followers never consult it."""
        if registry is None:
            from heat2d_tpu.obs import get_registry
            registry = get_registry()
        self.registry = registry
        self.default_timeout = default_timeout
        self.retry_policy = (RetryPolicy() if retry_policy is None
                             else retry_policy)
        #: launch wall-clock deadline; None = no watchdog (hangs bound
        #: only by the caller's own future timeout)
        self.launch_deadline = launch_deadline
        #: the clock the deadline is measured on (None = wall clock).
        #: Tests inject a controllable clock so deadline scenarios are
        #: deterministic on any host speed (resil/retry.Watchdog).
        self.deadline_clock = deadline_clock
        self.breaker = (DegradedMode(registry=registry) if breaker is None
                        else breaker)
        self.cache = ResultCache(cache_size, registry=registry)
        self.flight = SingleFlight(registry=registry)
        self.engine = (EnsembleEngine(registry=registry,
                                      max_batch=max_batch)
                       if engine is None else engine)
        max_batch = self.engine.max_batch
        self.admission = admission
        #: lazily-built inverse engine + its dedicated dispatch lane
        #: (heat2d_tpu/diff): optimization loops are long-lived host
        #: work, so they run on their own single-worker thread — an
        #: InverseRequest can never head-of-line-block solve launches
        #: on the scheduler thread. The stop event interrupts a
        #: running loop at its next iteration on non-drain shutdown.
        self._inv_engine = None
        self._inv_pool = None
        self._inv_stop = threading.Event()
        self.batcher = MicroBatcher(self._dispatch, max_batch=max_batch,
                                    max_delay=max_delay,
                                    max_queue=max_queue,
                                    registry=registry)
        self._started = False

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> "SolveServer":
        self._inv_stop.clear()
        self.batcher.start()
        self._started = True
        return self

    def stop(self, drain: bool = False) -> None:
        """Stop serving. ``drain=True`` is the graceful path (rolling
        worker restarts): admission closes, queued buckets flush, and
        every in-flight future is resolved before this returns — no
        admitted request is dropped across a drain (inverse
        optimizations run to completion). Default (False) rejects
        whatever is still queued with ``Rejected("shutdown")`` and
        interrupts a running inverse loop at its next iteration."""
        self._started = False
        if not drain:
            self._inv_stop.set()
        self.batcher.stop(drain=drain)
        pool, self._inv_pool = self._inv_pool, None
        if pool is not None:
            # Joins the inverse lane: on drain every dispatched loop
            # finished; otherwise the stop event aborts it within one
            # iteration — either way all futures are resolved here.
            pool.shutdown(wait=True)

    def __enter__(self) -> "SolveServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving ------------------------------------------------------- #

    def submit(self, req: SolveRequest,
               timeout: Optional[float] = None) -> Future:
        """Admit one request; the returned future resolves to a
        ``SolveResult`` or fails with a structured ``Rejected``.

        Accepts any request implementing the serving protocol
        (``validate``/``content_hash``/``signature``): plain solves
        dispatch to the ensemble engine, requests tagged
        ``request_kind == "inverse"`` (heat2d_tpu/diff) run their
        optimization loop through the same cache, single-flight,
        admission control, and retry/watchdog/breaker plumbing."""
        t0 = time.monotonic()
        timeout = self.default_timeout if timeout is None else timeout
        try:
            req.validate()
        except Rejected as e:
            self._count("rejected_invalid")
            return _failed(e)
        key = req.content_hash()

        # Tracing: one "serve.request" span per admission, child of any
        # context that arrived WITH the request (a fleet worker's wire
        # dispatch) — every downstream span (queue, launch) descends
        # from it via the attached context. NULL_SPAN when off: zero
        # bookkeeping, programs untouched (tests pin the jaxprs).
        span = tracing.NULL_SPAN
        if tracing.enabled():
            span = tracing.begin(
                "serve.request", kind="request",
                parent=request_trace(req), content_hash=key,
                signature=str(req.signature()))
            attach_trace(req, span.ctx)

        hit = self.cache.get(key)
        if hit is not None:
            # Cache hits are served even in degraded mode: the breaker
            # sheds COMPUTE, not answers we already hold. as_cache_hit
            # is the generic relabel every cacheable result type
            # (SolveResult, diff's InverseResult) implements.
            self._count("cache_hit")
            self._latency(t0)
            span.end(outcome="cache_hit")
            fut = Future()
            fut.set_result(hit.as_cache_hit())
            return fut

        fut, leader = self.flight.claim(key)
        if span is not tracing.NULL_SPAN:
            # one close per admission, whatever path answers it (a
            # follower's span closes when the leader's future does)
            if not leader:
                span.set(coalesced=True)
            fut.add_done_callback(
                lambda f: span.end(outcome=_outcome_of(f)))
        if leader and not self.breaker.allow():
            # Shed only work that would COST a launch: cache hits
            # (above) and coalesced followers of an already-in-flight
            # leader ride through — the breaker sheds compute, not
            # answers the server already owes.
            self._count("rejected_degraded")
            if self.registry is not None:
                self.registry.counter("serve_degraded_shed_total")
            self.flight.fail(key, Rejected(
                "degraded", "server is in degraded mode after repeated "
                "launch failures: uncached load is shed while the "
                "backend recovers", content_hash=key,
                breaker_state=self.breaker.state))
            return fut
        if leader and self.admission is not None:
            # Modeled mesh-capacity admission (mesh.MeshAdmission):
            # sheds on the resource model's saturation verdict, not
            # queue depth — only work that would COST a launch (cache
            # hits answered above, followers ride the leader).
            rej = self.admission.admit(req)
            if rej is not None:
                self._count("rejected_" + rej.code)
                self.flight.fail(key, rej)
                return fut
        if not leader:
            self._count("coalesced")
            out = coalesced_future(fut)
            out.add_done_callback(lambda _f: self._latency(t0))
            return out

        def fail(exc: BaseException) -> None:
            self._count(_outcome_label(exc))
            self.flight.fail(key, exc)

        try:
            self.batcher.submit(req, key, fail, timeout=timeout)
        except Rejected as e:
            fail(e)
        else:
            self._count("admitted")
        fut.add_done_callback(lambda _f: self._latency(t0))
        return fut

    def solve(self, req: SolveRequest,
              timeout: Optional[float] = None) -> SolveResult:
        """Synchronous convenience: submit + wait. Raises ``Rejected``."""
        wait = self.default_timeout if timeout is None else timeout
        # The queue deadline already bounds the wait; the extra slack
        # only guards against a wedged scheduler thread.
        return self.submit(req, timeout=timeout).result(
            None if wait is None else wait + 60)

    # -- dispatch (scheduler thread) ----------------------------------- #

    def _inverse_engine(self):
        """The inverse-request executor, built on first use — the serve
        package never imports heat2d_tpu/diff unless inverse traffic
        actually arrives. The engine aborts a loop (structured
        ``Rejected``) when it outlives ``launch_deadline`` or a
        non-drain stop is requested."""
        if self._inv_engine is None:
            from heat2d_tpu.diff.serving import InverseEngine
            self._inv_engine = InverseEngine(registry=self.registry,
                                             deadline=self.launch_deadline,
                                             stop_event=self._inv_stop,
                                             clock=self.deadline_clock)
        return self._inv_engine

    def _inverse_pool(self) -> ThreadPoolExecutor:
        if self._inv_pool is None:
            self._inv_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="heat2d-serve-inverse")
        return self._inv_pool

    def _dispatch(self, sig, batch) -> None:
        """Scheduler-thread entry: solve buckets run inline; inverse
        buckets hop to the dedicated lane so a multi-minute
        optimization loop cannot starve solve traffic into queue
        timeouts (every request the lane is handed is still delivered
        or failed by ``_dispatch_batch``)."""
        kind = getattr(batch[0].req, "request_kind", "solve")
        if kind == "inverse":
            self._inverse_pool().submit(self._dispatch_batch, sig,
                                        batch, kind)
            return
        self._dispatch_batch(sig, batch, kind)

    def _dispatch_batch(self, sig, batch, kind) -> None:
        """Bucket -> one launch (retried, watchdogged) -> per-request
        results. Transient launch failures retry with capped backoff;
        a launch that outlives ``launch_deadline`` has its waiters
        failed with ``Rejected("watchdog_timeout")`` by the watchdog
        thread (the launch itself keeps running — if it eventually
        returns, its results still warm the cache). Terminal failures
        fail every member's flight entry and feed the breaker.
        Inverse buckets (``request_kind == "inverse"``) run their
        optimization loops through the InverseEngine under the SAME
        retry/watchdog/breaker plumbing; their results are
        ``InverseResult`` objects that cache and resolve identically."""
        reqs = [p.req for p in batch]

        sig_str = str(sig)

        def on_timeout() -> None:
            if self.registry is not None:
                self.registry.counter("serve_watchdog_timeouts_total")
            exc = Rejected(
                "watchdog_timeout",
                f"launch exceeded the {self.launch_deadline}s deadline",
                signature=str(sig))
            for p in batch:
                self.flight.fail(p.key, exc)
                self._count("rejected_watchdog_timeout")
                self._sig_count(sig_str, "rejected_watchdog_timeout")
            self.breaker.record_failure()

        def on_retry(i: int, exc: BaseException) -> None:
            if self.registry is not None:
                self.registry.counter("serve_retries_total")
                self.registry.counter("serve_launch_failures_total")

        engine = (self._inverse_engine() if kind == "inverse"
                  else self.engine)
        watchdog = Watchdog(self.launch_deadline, on_timeout,
                            clock=self.deadline_clock)
        t_launch0 = time.monotonic()
        try:
            with watchdog:
                results = call_with_retries(
                    lambda: engine.solve_batch(reqs),
                    self.retry_policy, on_retry=on_retry)
        except BaseException as e:  # noqa: BLE001 — routed, not dropped
            if self.registry is not None:
                self.registry.counter("serve_launch_failures_total")
            if not watchdog.fired:
                # a fired watchdog already charged this launch to the
                # breaker in on_timeout — one launch, one verdict
                self.breaker.record_failure()
            self._emit_launch_spans(batch, t_launch0, time.monotonic(),
                                    kind, error=repr(e))
            # a structured rejection from the engine (e.g. the mesh
            # fault path's Rejected("mesh_stall")) keeps its code in
            # the outcome labels
            outcome = _outcome_label(e)
            for p in batch:
                self.flight.fail(p.key, e)
                self._count(outcome)
                self._sig_count(sig_str, outcome)
            return
        t_launch1 = time.monotonic()
        self._emit_launch_spans(batch, t_launch0, t_launch1, kind)
        if not watchdog.fired:
            # a launch that outlived its deadline is a failure even if
            # it eventually returned: its waiters were already rejected,
            # and a success here would reset the breaker a consistently
            # too-slow backend deserves to trip
            self.breaker.record_success()
        for p, r in zip(batch, results):
            if kind == "inverse":
                # The engine already built the full result; stamp the
                # serving labels (the flight key is authoritative).
                res = dataclasses.replace(r, content_hash=p.key,
                                          batch_size=len(batch))
            else:
                u, steps_done = r
                res = SolveResult(u=u, steps_done=steps_done,
                                  content_hash=p.key,
                                  batch_size=len(batch))
            self.cache.put(p.key, res)
            self.flight.resolve(p.key, res)
            self._count("completed_late" if watchdog.fired
                        else "completed")
            if not watchdog.fired:
                # A fired watchdog already charged every member to the
                # per-signature failure counters (on_timeout); a late
                # resolve must not ALSO count them completed or feed
                # failed-request latencies into the SLO sources — that
                # would halve the burn rate and pollute the p99.
                self._sig_count(sig_str, "completed")
                if self.registry is not None:
                    # admission -> launch-complete, per signature: the
                    # SLO evaluation's latency source (obs/slo.py)
                    self.registry.observe("serve_signature_latency_s",
                                          time.monotonic() - p.enqueued,
                                          signature=sig_str)

    # -- tracing ------------------------------------------------------- #

    def _emit_launch_spans(self, batch, t0: float, t1: float,
                           kind: str, error=None) -> None:
        """One "serve.launch" span per member, parented on that
        member's request span — a batch launch serves N traces, and
        per-request critical paths need the segment in each. The
        engine's launch row flags first launches (jit compile paid),
        which the trace CLI buckets as "compile"."""
        if not tracing.enabled():
            return
        attrs = {"occupancy": len(batch)}
        if error is not None:
            attrs["error"] = error
        elif kind != "inverse" and self.engine.launch_log:
            row = self.engine.launch_log[-1]
            attrs.update(capacity=row["capacity"],
                         first_launch=row.get("first_launch", False))
        for p in batch:
            tracing.emit("serve.launch", t0, t1, kind="launch",
                         parent=request_trace(p.req), **attrs)

    # -- metrics ------------------------------------------------------- #

    def _sig_count(self, sig_str: str, outcome: str) -> None:
        if self.registry is not None:
            self.registry.counter("serve_signature_requests_total",
                                  signature=sig_str, outcome=outcome)

    def _count(self, outcome: str) -> None:
        if self.registry is not None:
            self.registry.counter("serve_requests_total", outcome=outcome)

    def _latency(self, t0: float) -> None:
        if self.registry is not None:
            self.registry.observe("serve_e2e_latency_s",
                                  time.monotonic() - t0)


class Client:
    """Synchronous client for tests and the CLI. Requests may be given
    as ``SolveRequest`` objects or keyword fields."""

    def __init__(self, server: SolveServer):
        self.server = server

    def solve(self, req: Optional[SolveRequest] = None,
              timeout: Optional[float] = None, **fields) -> SolveResult:
        if req is None:
            req = SolveRequest.from_dict(fields)
        elif fields:
            raise ValueError("pass a SolveRequest or fields, not both")
        return self.server.solve(req, timeout=timeout)

    def submit(self, req: Optional[SolveRequest] = None,
               timeout: Optional[float] = None, **fields) -> Future:
        if req is None:
            req = SolveRequest.from_dict(fields)
        elif fields:
            raise ValueError("pass a SolveRequest or fields, not both")
        return self.server.submit(req, timeout=timeout)


def _outcome_label(exc: BaseException) -> str:
    """ONE copy of the failure->outcome-label mapping (submit path,
    launch path, span emission): a structured ``Rejected`` keeps its
    code — it is an answer, not an error."""
    return ("rejected_" + exc.code if isinstance(exc, Rejected)
            else "error")


def _outcome_of(f: Future) -> str:
    """The span/metric outcome label of a resolved future."""
    exc = f.exception()
    if exc is None:
        return "completed"
    return _outcome_label(exc)


def _failed(exc: BaseException) -> Future:
    fut = Future()
    fut.set_exception(exc)
    return fut


#: public alias — the fleet router shares the same failure path
failed_future = _failed


def coalesced_future(leader: Future) -> Future:
    """A derived future for a single-flight FOLLOWER: the leader's
    result re-labeled ``coalesced=True`` (the grid itself is shared,
    not copied), so the caller can see HOW it was served; the leader's
    failure propagates as-is. Shared by ``SolveServer`` and the fleet
    router."""
    out = Future()

    def _relabel(f: Future) -> None:
        exc = f.exception()
        if exc is not None:
            out.set_exception(exc)
        else:
            out.set_result(dataclasses.replace(
                f.result(), coalesced=True))

    leader.add_done_callback(_relabel)
    return out
