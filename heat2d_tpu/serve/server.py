"""The solve server: admission -> cache -> single-flight -> micro-batch
-> ensemble launch, instrumented end to end.

Request lifecycle (``SolveServer.submit``):

1. **Validate** — malformed specs get ``Rejected("invalid")`` before
   touching any shared state.
2. **Cache** — a content-hash hit returns a completed future
   immediately (the stored grid is the cold solve's output, bitwise).
3. **Single-flight** — an identical request already in flight attaches
   to the leader's future (one compute, N answers).
4. **Queue** — the leader enters the micro-batcher's signature bucket;
   over-depth load is shed at the door, queued requests can time out.
5. **Launch** — the scheduler thread dispatches the bucket as one
   ensemble launch through the per-signature compile cache; results
   fill the cache, resolve futures, and record latency.

``submit`` returns a ``concurrent.futures.Future[SolveResult]`` and
never raises (rejections arrive AS the future's exception, uniformly,
so async callers have one error path). ``Client`` is the synchronous
wrapper tests and the CLI use.

Metrics: ``serve_requests_total{outcome}`` counter and the
``serve_e2e_latency_s`` histogram here, plus everything the cache /
batcher / engine layers record (docs/SERVING.md has the full table).
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import Future
from typing import Optional

from heat2d_tpu.serve.batcher import MicroBatcher
from heat2d_tpu.serve.cache import ResultCache, SingleFlight
from heat2d_tpu.serve.engine import EnsembleEngine
from heat2d_tpu.serve.schema import Rejected, SolveRequest, SolveResult


class SolveServer:
    """In-process serving front end over the batched ensemble engine."""

    def __init__(self, *, max_batch: int = 8, max_delay: float = 0.005,
                 max_queue: int = 256, cache_size: int = 256,
                 default_timeout: Optional[float] = 30.0,
                 registry=None):
        if registry is None:
            from heat2d_tpu.obs import get_registry
            registry = get_registry()
        self.registry = registry
        self.default_timeout = default_timeout
        self.cache = ResultCache(cache_size, registry=registry)
        self.flight = SingleFlight(registry=registry)
        self.engine = EnsembleEngine(registry=registry,
                                     max_batch=max_batch)
        self.batcher = MicroBatcher(self._dispatch, max_batch=max_batch,
                                    max_delay=max_delay,
                                    max_queue=max_queue,
                                    registry=registry)
        self._started = False

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> "SolveServer":
        self.batcher.start()
        self._started = True
        return self

    def stop(self) -> None:
        self._started = False
        self.batcher.stop()

    def __enter__(self) -> "SolveServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving ------------------------------------------------------- #

    def submit(self, req: SolveRequest,
               timeout: Optional[float] = None) -> Future:
        """Admit one request; the returned future resolves to a
        ``SolveResult`` or fails with a structured ``Rejected``."""
        t0 = time.monotonic()
        timeout = self.default_timeout if timeout is None else timeout
        try:
            req.validate()
        except Rejected as e:
            self._count("rejected_invalid")
            return _failed(e)
        key = req.content_hash()

        hit = self.cache.get(key)
        if hit is not None:
            self._count("cache_hit")
            self._latency(t0)
            fut = Future()
            fut.set_result(SolveResult(
                u=hit.u, steps_done=hit.steps_done, content_hash=key,
                cache_hit=True, batch_size=hit.batch_size))
            return fut

        fut, leader = self.flight.claim(key)
        if not leader:
            self._count("coalesced")
            # A derived future: the leader's result re-labeled
            # coalesced=True (the grid itself is shared, not copied),
            # so the caller can see HOW it was served.
            out = Future()

            def _relabel(f: Future) -> None:
                exc = f.exception()
                if exc is not None:
                    out.set_exception(exc)
                else:
                    out.set_result(dataclasses.replace(
                        f.result(), coalesced=True))

            fut.add_done_callback(_relabel)
            out.add_done_callback(lambda _f: self._latency(t0))
            return out

        def fail(exc: BaseException) -> None:
            self._count("rejected_" + exc.code
                        if isinstance(exc, Rejected) else "error")
            self.flight.fail(key, exc)

        try:
            self.batcher.submit(req, key, fail, timeout=timeout)
        except Rejected as e:
            fail(e)
        else:
            self._count("admitted")
        fut.add_done_callback(lambda _f: self._latency(t0))
        return fut

    def solve(self, req: SolveRequest,
              timeout: Optional[float] = None) -> SolveResult:
        """Synchronous convenience: submit + wait. Raises ``Rejected``."""
        wait = self.default_timeout if timeout is None else timeout
        # The queue deadline already bounds the wait; the extra slack
        # only guards against a wedged scheduler thread.
        return self.submit(req, timeout=timeout).result(
            None if wait is None else wait + 60)

    # -- dispatch (scheduler thread) ----------------------------------- #

    def _dispatch(self, sig, batch) -> None:
        """Bucket -> one launch -> per-request results. Any engine error
        fails every member's flight entry (the batcher already guards
        the thread)."""
        try:
            results = self.engine.solve_batch([p.req for p in batch])
        except BaseException as e:  # noqa: BLE001 — routed, not dropped
            for p in batch:
                self.flight.fail(p.key, e)
                self._count("error")
            return
        for p, (u, steps_done) in zip(batch, results):
            res = SolveResult(u=u, steps_done=steps_done,
                              content_hash=p.key,
                              batch_size=len(batch))
            self.cache.put(p.key, res)
            self.flight.resolve(p.key, res)
            self._count("completed")

    # -- metrics ------------------------------------------------------- #

    def _count(self, outcome: str) -> None:
        if self.registry is not None:
            self.registry.counter("serve_requests_total", outcome=outcome)

    def _latency(self, t0: float) -> None:
        if self.registry is not None:
            self.registry.observe("serve_e2e_latency_s",
                                  time.monotonic() - t0)


class Client:
    """Synchronous client for tests and the CLI. Requests may be given
    as ``SolveRequest`` objects or keyword fields."""

    def __init__(self, server: SolveServer):
        self.server = server

    def solve(self, req: Optional[SolveRequest] = None,
              timeout: Optional[float] = None, **fields) -> SolveResult:
        if req is None:
            req = SolveRequest.from_dict(fields)
        elif fields:
            raise ValueError("pass a SolveRequest or fields, not both")
        return self.server.solve(req, timeout=timeout)

    def submit(self, req: Optional[SolveRequest] = None,
               timeout: Optional[float] = None, **fields) -> Future:
        if req is None:
            req = SolveRequest.from_dict(fields)
        elif fields:
            raise ValueError("pass a SolveRequest or fields, not both")
        return self.server.submit(req, timeout=timeout)


def _failed(exc: BaseException) -> Future:
    fut = Future()
    fut.set_exception(exc)
    return fut
