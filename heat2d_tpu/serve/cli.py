"""``heat2d-tpu-serve`` — the serving subsystem's driver.

Two modes:

- ``--selftest``: start an in-process server, fire a small mixed
  workload through the synchronous client (same-shape coalescing,
  mixed-shape bucketing, duplicate single-flight, a cache-hit repeat),
  then assert the serving invariants: fewer launches than requests, a
  nonzero batch-occupancy histogram, at least one cache hit, and
  bitwise-identical cached results. Exit 0 iff every check holds —
  the CI smoke job runs exactly this on CPU.
- ``--requests FILE.jsonl``: serve a file of request dicts (one JSON
  object per line), writing one result/rejection summary line each to
  stdout or ``--results-out``.

``--metrics-out PATH`` writes the run's telemetry as JSONL (registry
events + snapshot + a ``kind="serve"`` run record), the same envelope
as the solver CLI's ``--metrics-out``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-serve",
        description="solve-serving subsystem: async queue, shape-"
                    "bucketed micro-batching onto the ensemble engine, "
                    "content-addressed result cache")
    p.add_argument("--selftest", action="store_true",
                   help="run the in-process mixed-workload smoke test "
                        "(CPU unless --platform tpu) and exit nonzero "
                        "on any serving-invariant failure")
    p.add_argument("--requests", default=None, metavar="JSONL",
                   help="serve a file of request dicts, one JSON object "
                        "per line")
    p.add_argument("--results-out", default=None, metavar="PATH",
                   help="with --requests: write result summaries here "
                        "instead of stdout")
    s = p.add_argument_group("scheduler tuning (docs/SERVING.md)")
    s.add_argument("--max-batch", type=int, default=8,
                   help="members per ensemble launch (bucket dispatches "
                        "when full)")
    s.add_argument("--max-delay", type=float, default=0.005, metavar="S",
                   help="longest a bucket's oldest request waits before "
                        "dispatching a partial batch")
    s.add_argument("--queue-depth", type=int, default=256,
                   help="admission limit across all buckets; excess "
                        "load is shed with a structured rejection")
    s.add_argument("--cache-size", type=int, default=256,
                   help="result-cache entries (content-addressed LRU)")
    s.add_argument("--timeout", type=float, default=30.0,
                   help="per-request queue timeout in seconds")
    m = p.add_argument_group("mesh serving (docs/SERVING.md)")
    m.add_argument("--mesh", action="store_true",
                   help="serve through the mesh-aware engine "
                        "(heat2d_tpu/mesh): buckets shard over every "
                        "attached device on the batch axis, huge-grid "
                        "signatures dispatch through the fused-halo "
                        "spatial route, per-bucket split recorded; "
                        "--max-batch then bounds members PER CHIP")
    m.add_argument("--mesh-admission-mcells", type=float, default=None,
                   metavar="R",
                   help="with --mesh: arm modeled-capacity admission "
                        "control at R Mcells/s per chip (default: "
                        "admission off; the tune db's measured rate "
                        "is consulted when armed without a rate)")
    m.add_argument("--mesh-stall-deadline", type=float, default=None,
                   metavar="S",
                   help="with --mesh: arm the hung-collective "
                        "watchdog — a WARM mesh launch stalling past "
                        "S seconds is quarantined + shrunk-and-"
                        "requeued instead of hanging forever "
                        "(docs/RESILIENCE.md failure model)")
    m.add_argument("--mesh-abft", action="store_true",
                   help="with --mesh: arm the ABFT checksum verify "
                        "tier (ops/abft.py) — silent data corruption "
                        "quarantines the device and recomputes")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write telemetry JSONL (events + snapshot + the "
                        "kind='serve' run record)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="arm distributed request tracing: per-request "
                        "spans (admission, queue, launch) land as JSONL "
                        "in DIR; merge with heat2d-tpu-trace DIR "
                        "(docs/OBSERVABILITY.md). Free when off")
    p.add_argument("--perf", action="store_true",
                   help="arm the performance observatory: per-program "
                        "cost cards (XLA cost/memory analysis at first "
                        "launch) + the perf_* roofline families; cards "
                        "persist beside the spans when --trace-dir is "
                        "set and ride the run record "
                        "(docs/OBSERVABILITY.md). Free when off")
    s2 = p.add_argument_group("SLO objectives (docs/OBSERVABILITY.md)")
    s2.add_argument("--slo-p99", type=float, default=None, metavar="S",
                    help="per-signature p99 latency target in seconds; "
                         "evaluation lands in the run record's 'slo' "
                         "rows and the slo_* gauges")
    s2.add_argument("--slo-error-budget", type=float, default=0.001,
                    metavar="F",
                    help="allowed failure fraction per signature "
                         "(default 0.001 = 99.9%%)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a JAX platform (selftest defaults to cpu)")
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"])
    return p


def _selftest_workload(client):
    """The mixed workload: returns (requests_fired, failures) and leaves
    its fingerprints in the registry/engine for the invariant checks."""
    from heat2d_tpu.serve.schema import SolveRequest

    a = [SolveRequest(nx=24, ny=32, steps=6, cx=0.05 + 0.01 * i, cy=0.1,
                      method="jnp") for i in range(6)]
    b = [SolveRequest(nx=16, ny=48, steps=6, cx=0.1, cy=0.05 + 0.01 * i,
                      method="jnp") for i in range(3)]
    dup = SolveRequest(nx=24, ny=32, steps=6, cx=0.2, cy=0.2,
                       method="jnp")

    failures = []
    # Same-shape coalescing + mixed shapes in separate buckets + two
    # identical in-flight duplicates, all submitted before the batcher's
    # max_delay elapses.
    futs = [client.submit(r) for r in a + b] + [client.submit(dup),
                                                client.submit(dup)]
    results = []
    for i, f in enumerate(futs):
        try:
            results.append(f.result(timeout=120))
        except Exception as e:  # noqa: BLE001 — report, don't crash
            failures.append(f"request {i} failed: {e!r}")
            results.append(None)
    fired = len(futs)

    if results[0] is not None:
        # Cache-hit repeat: bitwise-identical to the batched cold solve.
        import numpy as np
        again = client.solve(a[0], timeout=60)
        if not again.cache_hit:
            failures.append("repeat request was not a cache hit")
        if np.asarray(again.u).tobytes() != \
                np.asarray(results[0].u).tobytes():
            failures.append("cache hit result not bitwise-identical")
        fired += 1
    if results[-1] is not None and results[-2] is not None:
        import numpy as np
        if np.asarray(results[-1].u).tobytes() != \
                np.asarray(results[-2].u).tobytes():
            failures.append("coalesced duplicates returned different "
                            "grids")

    # Implicit route: a method="adi" request (diffusion numbers far
    # past the explicit stability box — the implicit win) must answer
    # through the real server path and answer bitwise-repeatably (the
    # repeat is a cache hit sharing the stored grid). The stronger
    # across-LAUNCH-CAPACITY pad-parity leg needs independent engines,
    # so it lives in analysis/implicit_gate.py leg 2 and
    # tests/test_implicit.py, not here.
    import numpy as np
    adi = SolveRequest(nx=24, ny=32, steps=4, cx=8.0, cy=6.0,
                       method="adi")
    try:
        first = client.solve(adi, timeout=120)
        again = client.solve(adi, timeout=60)
        fired += 2
        if not again.cache_hit:
            failures.append("adi repeat was not a cache hit")
        if np.asarray(again.u).tobytes() != \
                np.asarray(first.u).tobytes():
            failures.append("adi repeat not bitwise-identical")
    except Exception as e:  # noqa: BLE001 — report, don't crash
        failures.append(f"adi request failed: {e!r}")

    f2, fail2 = _problems_workload(client)
    return fired + f2, failures + fail2


def _problems_workload(client):
    """Every registered problem family end-to-end through the real
    server path (admission -> bucketing -> ensemble launch), plus the
    capability matrix's structured-rejection leg: reactdiff (nonlinear)
    x adi must come back ``Rejected("unsupported_combination")``
    NAMING the combination, never a crash (docs/PROBLEMS.md)."""
    import numpy as np

    from heat2d_tpu.serve.schema import Rejected, SolveRequest
    from heat2d_tpu.vocab import PROBLEMS

    fired = 0
    failures = []
    for fam in PROBLEMS:
        if fam == "heat5":
            continue    # the whole rest of the selftest is heat5
        req = SolveRequest(nx=16, ny=16, steps=5, cx=0.1, cy=0.1,
                           method="jnp", problem=fam)
        try:
            r = client.solve(req, timeout=120)
            fired += 1
            u = np.asarray(r.u)
            if u.shape != (16, 16) or not np.isfinite(u).all():
                failures.append(f"problem {fam}: bad result "
                                f"(shape {u.shape})")
        except Exception as e:  # noqa: BLE001 — report, don't crash
            failures.append(f"problem {fam} request failed: {e!r}")
    bad = SolveRequest(nx=16, ny=16, steps=5, cx=0.1, cy=0.1,
                       method="adi", problem="reactdiff")
    try:
        client.solve(bad, timeout=60)
        failures.append("reactdiff x adi was served (expected the "
                        "unsupported_combination rejection)")
    except Rejected as e:
        if e.code != "unsupported_combination":
            failures.append(f"reactdiff x adi rejected with "
                            f"{e.code!r}, expected "
                            f"'unsupported_combination'")
        elif "reactdiff" not in e.message:
            failures.append("unsupported_combination rejection does "
                            "not name the problem")
    except Exception as e:  # noqa: BLE001 — report, don't crash
        failures.append(f"reactdiff x adi raised {e!r} instead of a "
                        f"structured rejection")
    return fired, failures


def _mesh_kwargs(args, registry) -> dict:
    """engine/admission kwargs for ``SolveServer`` when ``--mesh``:
    the mesh-aware engine over every attached device, plus modeled-
    capacity admission when a rate was given."""
    if not args.mesh:
        return {}
    from heat2d_tpu.mesh import MeshAdmission, MeshEnsembleEngine
    fault = None
    if (getattr(args, "mesh_stall_deadline", None) is not None
            or getattr(args, "mesh_abft", False)):
        from heat2d_tpu.mesh import FaultPolicy
        fault = FaultPolicy(
            stall_deadline_s=args.mesh_stall_deadline,
            abft=bool(args.mesh_abft))
    # --max-batch becomes the PER-CHIP bound: the engine's launch
    # bound scales with the mesh instead of discarding the flag.
    out = {"engine": MeshEnsembleEngine(
        registry=registry, max_batch_per_chip=args.max_batch,
        fault=fault)}
    if args.mesh_admission_mcells is not None:
        out["admission"] = MeshAdmission(
            registry=registry,
            per_chip_mcells_per_s=args.mesh_admission_mcells)
    return out


def run_selftest(args, registry) -> int:
    from heat2d_tpu.serve.server import Client, SolveServer

    server = SolveServer(
        max_batch=args.max_batch, max_delay=max(args.max_delay, 0.05),
        max_queue=args.queue_depth, cache_size=args.cache_size,
        default_timeout=args.timeout, registry=registry,
        **_mesh_kwargs(args, registry))
    with server:
        fired, failures = _selftest_workload(Client(server))

    snap = registry.snapshot()
    occ = snap["histograms"].get("serve_batch_occupancy")
    launches = server.engine.launches
    if launches >= fired:
        failures.append(f"no batching: {launches} launches for {fired} "
                        f"requests")
    if not occ or occ["count"] < 1 or occ["sum"] < 1:
        failures.append("batch-occupancy metric is empty")
    elif occ["max"] < 2:
        failures.append("no launch held more than one member")
    if snap["counters"].get("serve_cache_hits_total", 0) < 1:
        failures.append("no cache hit recorded")
    if "serve_e2e_latency_s" not in snap["histograms"]:
        failures.append("no end-to-end latency recorded")
    from heat2d_tpu.vocab import PROBLEMS
    for fam in PROBLEMS:
        if fam == "heat5":
            continue
        if snap["counters"].get(
                f"problem_requests_total{{problem={fam}}}", 0) < 1:
            failures.append(f"no launch counted for problem {fam}")

    print(f"selftest: {fired} requests -> {launches} launches, "
          f"occupancy max {occ['max'] if occ else 0:.0f}, "
          f"cache hits "
          f"{snap['counters'].get('serve_cache_hits_total', 0):.0f}")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    _write_metrics(args, registry, server,
                   extra={"selftest_requests": fired,
                          "selftest_failures": failures})
    print("selftest " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


def run_requests(args, registry) -> int:
    from heat2d_tpu.serve.schema import Rejected, SolveRequest
    from heat2d_tpu.serve.server import SolveServer

    try:
        with open(args.requests) as f:
            dicts = [json.loads(line) for line in f
                     if line.strip()]
    except (OSError, json.JSONDecodeError) as e:
        print(f"bad --requests file: {e}\nQuitting...", file=sys.stderr)
        return 1

    out = (open(args.results_out, "w") if args.results_out
           else sys.stdout)
    server = SolveServer(
        max_batch=args.max_batch, max_delay=args.max_delay,
        max_queue=args.queue_depth, cache_size=args.cache_size,
        default_timeout=args.timeout, registry=registry,
        **_mesh_kwargs(args, registry))
    rc = 0
    try:
        with server:
            futs = []
            for d in dicts:
                try:
                    futs.append(server.submit(SolveRequest.from_dict(d)))
                except Rejected as e:   # from_dict validation
                    futs.append(None)
                    out.write(json.dumps(e.to_record()) + "\n")
            for fut in futs:
                if fut is None:
                    continue
                try:
                    out.write(json.dumps(
                        fut.result(timeout=args.timeout + 60)
                        .summary()) + "\n")
                except Rejected as e:
                    rc = 1
                    out.write(json.dumps(e.to_record()) + "\n")
                except Exception as e:  # noqa: BLE001
                    rc = 1
                    out.write(json.dumps(
                        {"rejected": "error", "message": repr(e)}) + "\n")
        _write_metrics(args, registry, server,
                       extra={"requests": len(dicts)})
    finally:
        if out is not sys.stdout:
            out.close()
    return rc


def _write_metrics(args, registry, server, extra=None) -> None:
    extra = dict(extra or {})
    if args.slo_p99 is not None:
        # SLO evaluation at export time (never on the serving path):
        # slo_* gauges into the registry + the 'slo' record rows.
        from heat2d_tpu.obs import slo
        rows = slo.evaluate(
            registry, prefix="serve",
            default=slo.SLOPolicy(latency_p99_s=args.slo_p99,
                                  error_budget=args.slo_error_budget))
        slo.stamp_record(extra, rows)
        for r in rows:
            if not r.get("ok", True):
                print(f"SLO VIOLATION: {r['signature']}: p99 "
                      f"{r['p99_s']} vs target "
                      f"{r['latency_target_p99_s']}, burn rate "
                      f"{r['burn_rate']:.2f}", file=sys.stderr)
    if args.trace_dir:
        from heat2d_tpu.obs import tracing
        t = tracing.tracer()
        extra["trace"] = {"dir": args.trace_dir,
                          "spans_emitted": (t.spans_emitted
                                            if t is not None else 0)}
    if getattr(server.engine, "scheduler", None) is not None:
        # Mesh provenance (docs/SERVING.md): the per-signature split
        # decisions and the halo plans — with the compiled stamp the
        # spatial route flips when its mesh program really builds.
        extra["mesh"] = {
            "n_devices": server.engine.n_devices,
            "decisions": list(
                server.engine.scheduler.decisions().values()),
            "halo_plans": {str(sig): plan for sig, plan
                           in server.engine.halo_plans.items()},
        }
        fault = server.engine.fault_snapshot()
        if fault is not None:
            # Fault provenance (docs/RESILIENCE.md): the quarantine
            # book, measured recovery episodes, and the
            # no-quarantined-serving invariant verdict.
            extra["mesh"]["fault"] = fault
    from heat2d_tpu.obs import perf
    obs = perf.observer()
    if obs is not None:
        # the card book rides the record (docs/OBSERVABILITY.md cost-
        # card fields) and the JSONL sidecar is flushed closed
        extra["perf"] = obs.snapshot()
        perf.uninstall()
    if not args.metrics_out:
        return
    from heat2d_tpu.obs.record import build_record

    record = build_record("serve", extra={
        "launches": server.engine.launches,
        "launch_log": [
            {"signature": list(map(str, row["signature"])),
             "occupancy": row["occupancy"],
             "capacity": row["capacity"],
             "tuned_config": row.get("tuned_config"),
             **({"perf": row["perf"]} if "perf" in row else {}),
             **({"mesh": row["mesh"]} if "mesh" in row else {})}
            for row in server.engine.launch_log],
        # Per-signature tuned-config pre-resolve (docs/TUNING.md):
        # which signatures run measured kernel configs vs heuristics.
        "tuned_config": [t for t in server.engine.tuned.values()
                         if t is not None],
        **(extra or {})})
    registry.write_jsonl(args.metrics_out,
                         extra_records=[{"event": "run_record", **record}])


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not args.mesh:
        # mesh-dependent flags without --mesh would silently serve on
        # the plain single-chip engine while LOOKING fault-armed /
        # admission-priced — a usage error (rc 2), same contract as
        # the fleet CLI's rollout-dependent flags.
        for flag, armed in (
                ("--mesh-stall-deadline",
                 args.mesh_stall_deadline is not None),
                ("--mesh-abft", args.mesh_abft),
                ("--mesh-admission-mcells",
                 args.mesh_admission_mcells is not None)):
            if armed:
                parser.error(f"{flag} requires --mesh")
    if args.log_level:
        import logging
        logging.basicConfig(
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
        logging.getLogger("heat2d_tpu").setLevel(
            getattr(logging, args.log_level.upper()))
    platform = args.platform or ("cpu" if args.selftest else None)
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        import jax
        jax.config.update("jax_platforms", platform)

    if args.trace_dir:
        # explicit flag wins over any stale env var — otherwise the
        # campaign silently splits across two directories
        os.environ["HEAT2D_TRACE_DIR"] = args.trace_dir
        from heat2d_tpu.obs import tracing
        tracing.install(tracing.Tracer(args.trace_dir, service="serve"))

    from heat2d_tpu.obs import MetricsRegistry
    registry = MetricsRegistry()

    if args.perf:
        # cost cards share the trace campaign's directory when one is
        # armed (heat2d-tpu-trace --stats joins them on signature)
        from heat2d_tpu.obs import perf
        perf.install(perf.PerfObserver(registry=registry,
                                       dir=args.trace_dir,
                                       service="serve"))

    if args.selftest:
        return run_selftest(args, registry)
    if args.requests:
        return run_requests(args, registry)
    print("nothing to do: pass --selftest or --requests FILE.jsonl "
          "(a network listener is deliberately out of scope — embed "
          "SolveServer in your process; docs/SERVING.md)",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
