"""Solve-serving wire schema — requests, results, structured rejection.

A ``SolveRequest`` is the FULL problem spec of one solve: grid shape,
dtype, diffusivities, step/convergence schedule, and kernel method.
Two derived keys drive the whole serving stack:

- ``content_hash()`` — sha256 over the canonical spec. Two requests with
  the same hash are the same computation, so they share a result-cache
  entry and coalesce in flight (serve/cache.py single-flight).
- ``signature()`` — the spec minus the per-member diffusivities. Two
  requests with the same signature compile to the SAME executable
  (cx/cy are traced operands of the batched ensemble runners —
  models/ensemble.batch_runner), so the micro-batcher buckets by it and
  dispatches each bucket as one ensemble launch.

Everything here is host-side plain data; nothing imports jax, so schema
validation and hashing stay cheap on the admission path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

from heat2d_tpu.vocab import DEFAULT_PROBLEM, PROBLEMS, SERVE_METHODS

#: dtypes the batched ensemble runners are validated for (the reference
#: stores f32; accum-dtype promotion is a CLI-solver concern, rejected
#: at the ensemble entry — cli.py's unsupported-flag check).
SUPPORTED_DTYPES = ("float32",)

#: "adi"/"mg" are the implicit time-stepping routes (ops/tridiag.py,
#: ops/multigrid.py): unconditionally stable, so a request's (cx, cy)
#: are dt-scaled diffusion numbers far past the explicit kx+ky <= 1/2
#: box — the ensemble runners dispatch them like any other method and
#: the whole serving stack (signature bucketing, padded-capacity
#: compile ladder, mesh sharding) absorbs them unchanged. Derived from
#: the single-source method vocabulary (heat2d_tpu/vocab.py).
SUPPORTED_METHODS = SERVE_METHODS

#: Problem families a request may name (the spatial-operator axis,
#: heat2d_tpu/problems/): per-family capability is validated at
#: admission (method x problem from the declared matrix), so an
#: unsupported combination is a structured rejection, never a crash.
SUPPORTED_PROBLEMS = PROBLEMS


class Rejected(Exception):
    """Structured admission/serving rejection — load shedding, queue
    timeout, shutdown. ``code`` is machine-readable; ``to_record()`` is
    the JSONL shape the CLI and metrics events emit."""

    def __init__(self, code: str, message: str, **fields):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.fields = fields

    def to_record(self) -> dict:
        return {"rejected": self.code, "message": self.message,
                **self.fields}


@dataclasses.dataclass(frozen=True)
class SolveRequest:
    """One solve: the reference's compile-time ``#define`` set as a
    serving payload. Frozen: the hash/signature of an admitted request
    must not drift while it sits in the queue."""

    nx: int
    ny: int
    steps: int
    cx: float = 0.1
    cy: float = 0.1
    dtype: str = "float32"
    method: str = "auto"
    convergence: bool = False
    interval: int = 20
    sensitivity: float = 0.1
    #: spatial-operator family (SUPPORTED_PROBLEMS). The default
    #: "heat5" keeps every pre-registry request's spec, hash, and
    #: signature unchanged (back-compat: load/replay.py parses
    #: problem-less legacy signatures as heat5).
    problem: str = "heat5"
    #: distributed-tracing context (obs/tracing.TraceContext) riding
    #: BESIDE the problem spec: compare=False keeps it out of eq/hash,
    #: and spec()/content_hash()/signature() never read it — two
    #: requests differing only in trace are the SAME computation
    #: (same cache entry, same bucket). Not a wire field: from_dict
    #: rejects it (the fleet wire carries trace in its own envelope
    #: key, never inside the request spec).
    trace: "object" = dataclasses.field(
        default=None, compare=False, repr=False)

    def validate(self) -> "SolveRequest":
        if self.nx < 3 or self.ny < 3:
            raise Rejected("invalid", f"grid must be at least 3x3, got "
                           f"{self.nx}x{self.ny}")
        if self.steps < 0:
            raise Rejected("invalid", f"steps must be >= 0, got "
                           f"{self.steps}")
        if self.dtype not in SUPPORTED_DTYPES:
            raise Rejected("invalid", f"dtype {self.dtype!r} not in "
                           f"{SUPPORTED_DTYPES}")
        if self.method not in SUPPORTED_METHODS:
            raise Rejected("invalid", f"method {self.method!r} not in "
                           f"{SUPPORTED_METHODS}")
        if self.problem not in SUPPORTED_PROBLEMS:
            raise Rejected("invalid", f"problem {self.problem!r} not "
                           f"in {SUPPORTED_PROBLEMS}")
        if self.problem != DEFAULT_PROBLEM:
            # Capability matrix (problems/base.py, jax-free): an
            # unsupported method x problem combination is a structured
            # rejection NAMING the combination, never a crash.
            from heat2d_tpu.problems.base import spec_for
            spec = spec_for(self.problem)
            ok, reason = spec.supports_method(self.method)
            if not ok:
                raise Rejected("unsupported_combination", reason,
                               problem=self.problem,
                               method=self.method)
            if min(self.nx, self.ny) < spec.min_grid:
                raise Rejected(
                    "invalid",
                    f"problem {self.problem!r} (halo width "
                    f"{spec.halo_width}) needs a grid of at least "
                    f"{spec.min_grid}x{spec.min_grid}, got "
                    f"{self.nx}x{self.ny}")
        if self.convergence and self.interval < 1:
            raise Rejected("invalid", f"interval must be >= 1, got "
                           f"{self.interval}")
        return self

    def schedule(self) -> tuple:
        """The (interval, sensitivity) pair as COMPUTED: canonicalized
        to (0, 0.0) on fixed-step runs, where the convergence knobs are
        unused — they must not fragment cache entries, batch buckets,
        or compiled runners."""
        if self.convergence:
            return int(self.interval), float(self.sensitivity)
        return 0, 0.0

    def spec(self) -> dict:
        """The canonical spec dict (all hashed fields, fixed order).
        ``method`` hashes UNRESOLVED on purpose: resolving ``auto``
        needs jax (and is device-dependent — two hosts can pick
        different kernels), so the spec stays plain data and 'auto'
        is its own cache/bucket key."""
        interval, sensitivity = self.schedule()
        d = {
            "nx": int(self.nx), "ny": int(self.ny),
            "steps": int(self.steps),
            "cx": float(self.cx), "cy": float(self.cy),
            "dtype": self.dtype, "method": self.method,
            "convergence": bool(self.convergence),
            "interval": interval,
            "sensitivity": sensitivity,
        }
        if self.problem != "heat5":
            # heat5 hashes the pre-registry spec byte-identically (its
            # cache keys and signature hashes are untouched by the
            # registry); other families are their own cache entries.
            d["problem"] = self.problem
        return d

    def content_hash(self) -> str:
        """sha256 over the canonical JSON spec. repr-exact floats: two
        requests hash equal iff they are the same computation bit-for-
        bit (0.1 and 0.1000000001 are different cache entries)."""
        blob = json.dumps(self.spec(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    def signature(self) -> tuple:
        """The compiled-signature bucket key: every spec field EXCEPT
        (cx, cy), which ride as traced operands through one executable.
        Requests sharing a signature batch into one ensemble launch.

        The problem family rides at index 8 — but ONLY for non-heat5
        families: heat5 keeps the pre-registry 8-tuple byte-identical,
        so its content hashes, rendezvous routing weights, recorded
        trace campaigns, and tune-db consults are untouched by the
        registry. load/replay.py parses both generations (8-tuples as
        problem="heat5")."""
        base = (self.nx, self.ny, self.steps, self.dtype, self.method,
                self.convergence) + self.schedule()
        if self.problem == "heat5":
            return base
        return base + (self.problem,)

    @classmethod
    def from_dict(cls, d: dict) -> "SolveRequest":
        # 'trace' is deliberately NOT a request field on the wire: the
        # spec is the computation, the trace context is an envelope
        # concern (fleet/wire.py carries it beside the spec).
        known = {f.name for f in dataclasses.fields(cls)} - {"trace"}
        bad = set(d) - known
        if bad:
            raise Rejected("invalid",
                           f"unknown request fields: {sorted(bad)}")
        try:
            return cls(**d).validate()
        except TypeError as e:
            raise Rejected("invalid", str(e)) from None


def attach_trace(req, ctx) -> None:
    """Attach a tracing context to a (frozen) request IN PLACE. Works
    for any request implementing the serving protocol (SolveRequest,
    diff's InverseRequest) — the context is observability metadata,
    excluded from hash/signature/eq by contract, so mutating it never
    changes what the request MEANS."""
    try:
        object.__setattr__(req, "trace", ctx)
    except (AttributeError, TypeError):
        pass    # slotted duck-types without the field: trace is lost,
        #         the request still serves


def request_trace(req):
    """The attached tracing context, or None."""
    return getattr(req, "trace", None)


@dataclasses.dataclass
class SolveResult:
    """One served solve. ``u`` is the final (nx, ny) grid (host numpy);
    ``steps_done`` is the per-member iteration count on convergence runs
    (== steps on fixed-step). ``cache_hit`` / ``coalesced`` say how the
    request was served; ``batch_size`` is the occupancy of the launch
    that computed it (1 for a cache hit's original cold solve)."""

    u: "object"
    steps_done: int
    content_hash: str
    cache_hit: bool = False
    coalesced: bool = False
    batch_size: int = 1

    def as_cache_hit(self) -> "SolveResult":
        """The stored result re-labeled for a cache-hit answer (the
        grid is shared, not copied) — part of the generic serving
        protocol every cacheable result type implements
        (InverseResult mirrors it)."""
        return dataclasses.replace(self, cache_hit=True,
                                   coalesced=False)

    def summary(self) -> dict:
        """JSON-safe row for the CLI's results stream (the grid itself
        stays out — final_m<i>.dat-style dumps are the CLI's job)."""
        import numpy as np
        u = np.asarray(self.u)
        return {
            "content_hash": self.content_hash,
            "steps_done": int(self.steps_done),
            "cache_hit": bool(self.cache_hit),
            "coalesced": bool(self.coalesced),
            "batch_size": int(self.batch_size),
            "shape": list(u.shape),
            "max_temperature": float(u.max()),
            "total_heat": float(u.sum()),
        }
