"""Content-addressed result cache + single-flight deduplication.

The cache is a bounded LRU keyed by ``SolveRequest.content_hash()``:
identical repeat requests return the stored result without touching the
queue (bitwise-identical — the stored grid IS the cold solve's output,
never recomputed). Single-flight covers the window BEFORE a result
exists: identical requests already in flight coalesce onto the leader's
future, so N duplicates cost one compute and one cache fill.

Metrics (obs/metrics.py registry, optional): ``serve_cache_hits_total``,
``serve_cache_misses_total``, ``serve_cache_evictions_total`` counters,
``serve_cache_size`` / ``serve_cache_hit_rate`` gauges,
``serve_coalesced_total`` counter.
"""

from __future__ import annotations

import collections
from concurrent.futures import Future
from typing import Optional

from heat2d_tpu.analysis.locks import AuditedLock, guarded_by


@guarded_by("_lock", "hits", "misses", "evictions")
class ResultCache:
    """Bounded LRU over content hashes. Thread-safe: admission runs on
    caller threads, fills on the scheduler thread. ``prefix`` names the
    metric family (``serve_cache`` here, ``fleet_cache`` for the
    fleet's shared cross-worker cache — same structure, separate
    counters)."""

    def __init__(self, capacity: int = 256, registry=None,
                 prefix: str = "serve_cache"):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self.registry = registry
        self.prefix = prefix
        self._lock = AuditedLock(prefix)
        self._data: "collections.OrderedDict" = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: str):
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self.hits += 1
                value = self._data[key]
            else:
                self.misses += 1
                value = None
        self._record(hit=value is not None)
        return value

    def put(self, key: str, value) -> None:
        if self.capacity == 0:
            return
        evicted = 0
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                evicted += 1
            self.evictions += evicted
        if evicted and self.registry is not None:
            self.registry.counter(self.prefix + "_evictions_total",
                                  evicted)
        self._record()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def _record(self, hit: Optional[bool] = None) -> None:
        r = self.registry
        if r is None:
            return
        if hit is True:
            r.counter(self.prefix + "_hits_total")
        elif hit is False:
            r.counter(self.prefix + "_misses_total")
        r.gauge(self.prefix + "_size", len(self))
        total = self.hits + self.misses
        if total:
            r.gauge(self.prefix + "_hit_rate", self.hits / total)


class SingleFlight:
    """In-flight deduplication: the first caller for a key becomes the
    LEADER and owns the returned Future; later callers for the same key
    (while it is unresolved) get the SAME Future back. Coalesced
    requests share the leader's fate — result or rejection."""

    def __init__(self, registry=None, counter: str = "serve_coalesced_total"):
        self._lock = AuditedLock("single_flight")
        self._inflight: dict = {}
        self.registry = registry
        self._counter = counter

    def claim(self, key: str):
        """(future, leader): ``leader`` is True when this caller must
        actually perform the work and later call ``resolve``/``fail``."""
        with self._lock:
            fut = self._inflight.get(key)
            if fut is not None:
                if self.registry is not None:
                    self.registry.counter(self._counter)
                return fut, False
            fut = Future()
            self._inflight[key] = fut
            return fut, True

    def _pop(self, key: str) -> Optional[Future]:
        with self._lock:
            return self._inflight.pop(key, None)

    def resolve(self, key: str, value) -> None:
        fut = self._pop(key)
        if fut is not None and not fut.done():
            fut.set_result(value)

    def fail(self, key: str, exc: BaseException) -> None:
        fut = self._pop(key)
        if fut is not None and not fut.done():
            fut.set_exception(exc)

    def __len__(self) -> int:
        with self._lock:
            return len(self._inflight)
