"""Async request queue + shape-bucketed micro-batching scheduler.

Admission puts each request into the bucket of its compiled signature
(``SolveRequest.signature()`` — shape/dtype/steps-class/method). A
single scheduler thread dispatches a bucket as ONE downstream launch
when it reaches ``max_batch`` members or its oldest member has waited
``max_delay`` seconds — the classic latency/occupancy trade of an
inference micro-batcher: ``max_delay`` bounds the latency a lone
request pays, ``max_batch`` bounds the work one launch amortizes.

Admission control:
- queue depth limit (``max_queue``, across all buckets): excess load is
  SHED at submit time with a structured ``Rejected("queue_full")`` —
  the caller hears immediately instead of timing out deep in a queue;
- per-request timeout: a request whose deadline passes while queued is
  rejected ``Rejected("timeout")`` by the scheduler, never dispatched.

The scheduler thread is the only consumer; submission is thread-safe
from any number of producers (the "async" front half — a
``concurrent.futures.Future`` per request, awaitable from asyncio via
``asyncio.wrap_future``).

Metrics: ``serve_queue_depth`` gauge, ``serve_queue_wait_s`` histogram
(admission -> dispatch, the time-to-first-dispatch), ``serve_batch_
occupancy`` / ``serve_batch_fill`` histograms, ``serve_dispatch_total``
and ``serve_rejected_total{reason}`` counters.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Optional

from heat2d_tpu.analysis.locks import AuditedCondition, guarded_by
from heat2d_tpu.obs import tracing
from heat2d_tpu.serve.schema import Rejected, SolveRequest, request_trace

log = logging.getLogger("heat2d_tpu.serve")


class Pending:
    """One queued request: the admission-time context the scheduler
    needs — bucket key, deadline, and the failure hook that rejects the
    caller's future."""

    __slots__ = ("req", "key", "enqueued", "deadline", "fail")

    def __init__(self, req: SolveRequest, key: str,
                 fail: Callable[[BaseException], None],
                 timeout: Optional[float], now: float):
        self.req = req
        self.key = key
        self.fail = fail
        self.enqueued = now
        self.deadline = None if timeout is None else now + timeout


@guarded_by("_cond", "_depth", "_running", "_draining")
class MicroBatcher:
    """The queue + scheduler. ``dispatch(signature, pendings)`` runs on
    the scheduler thread and must deliver/fail every pending it is
    handed (serve/server.py wires it to the ensemble engine)."""

    def __init__(self, dispatch: Callable, *, max_batch: int = 8,
                 max_delay: float = 0.005, max_queue: int = 256,
                 registry=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_delay < 0:
            raise ValueError(f"max_delay must be >= 0, got {max_delay}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._dispatch = dispatch
        self.max_batch = max_batch
        self.max_delay = max_delay
        self.max_queue = max_queue
        self.registry = registry
        self._cond = AuditedCondition("serve.batcher")
        #: signature -> FIFO of Pending (insertion order = arrival order)
        self._buckets: "collections.OrderedDict" = collections.OrderedDict()
        self._depth = 0
        self._running = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> None:
        with self._cond:
            if self._running:
                return
            if self._thread is not None and self._thread.is_alive():
                # The previous scheduler is still inside a dispatch
                # (stop() timed out waiting for it); a second consumer
                # over the same buckets would double-pop and corrupt
                # _depth.
                raise RuntimeError(
                    "scheduler thread from a previous start() is still "
                    "finishing a dispatch; retry stop()/start() later")
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="heat2d-serve-batcher",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain: bool = False) -> None:
        """Stop the scheduler. Default: anything still queued is
        rejected with ``Rejected("shutdown")`` (callers must not hang
        forever on a future nobody will fill).

        ``drain=True`` is the graceful path rolling worker restarts
        need: admission closes immediately (new submits reject), but
        the scheduler keeps dispatching — partial buckets flush without
        waiting out ``max_delay`` — until the queue is EMPTY, and only
        then exits. Because dispatch runs synchronously on the
        scheduler thread, when ``stop(drain=True)`` returns every
        admitted request has been resolved or failed; none were
        dropped."""
        with self._cond:
            if drain and self._running:
                self._draining = True
            else:
                self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
        with self._cond:
            self._running = False
            self._draining = False
        if self._thread is not None:
            if self._thread.is_alive():
                # A wedged dispatch: keep the handle so start() refuses
                # to spawn a concurrent consumer next to it.
                log.warning("scheduler thread did not exit within 60s; "
                            "a dispatch is still in flight")
            else:
                self._thread = None
        leftovers = []
        with self._cond:
            for q in self._buckets.values():
                leftovers.extend(q)
            self._buckets.clear()
            self._depth = 0
        for p in leftovers:
            self._reject(p, Rejected("shutdown", "server stopping",
                                     content_hash=p.key))
        self._gauge_depth()

    # -- admission ----------------------------------------------------- #

    def submit(self, req: SolveRequest, key: str,
               fail: Callable[[BaseException], None],
               timeout: Optional[float] = None) -> None:
        """Admit one request, or raise ``Rejected("queue_full")`` /
        ``Rejected("shutdown")`` — load shedding happens HERE, at the
        door, not after a queue wait."""
        now = time.monotonic()
        p = Pending(req, key, fail, timeout, now)
        with self._cond:
            if not self._running or self._draining:
                raise Rejected(
                    "shutdown",
                    "server draining" if self._draining
                    else "server not running", content_hash=key)
            if self._depth >= self.max_queue:
                if self.registry is not None:
                    self.registry.counter("serve_rejected_total",
                                          reason="queue_full")
                raise Rejected(
                    "queue_full",
                    f"queue depth {self._depth} at limit "
                    f"{self.max_queue}", content_hash=key)
            sig = req.signature()
            if sig not in self._buckets:
                self._buckets[sig] = collections.deque()
            self._buckets[sig].append(p)
            self._depth += 1
            self._cond.notify_all()
        self._gauge_depth()

    def depth(self) -> int:
        with self._cond:
            return self._depth

    # -- scheduler ----------------------------------------------------- #

    def _loop(self) -> None:
        while True:
            expired, batch, sig = [], None, None
            with self._cond:
                if not self._running:
                    return
                if self._draining and self._depth == 0:
                    self._running = False
                    return              # drained dry: a clean exit
                now = time.monotonic()
                expired = self._pop_expired_locked(now)
                sig, batch = self._pop_ready_locked(
                    now, drain=self._draining)
                if not expired and batch is None:
                    self._cond.wait(timeout=self._wake_in_locked(now))
                    continue
            for p in expired:
                self._reject(p, Rejected(
                    "timeout", "request timed out in queue",
                    content_hash=p.key,
                    waited_s=round(time.monotonic() - p.enqueued, 6)))
            if batch is not None:
                self._gauge_depth()
                self._record_batch(sig, batch)
                try:
                    self._dispatch(sig, batch)
                except BaseException as e:  # noqa: BLE001 — must not
                    #                         kill the scheduler thread
                    for p in batch:
                        self._reject(p, e)

    def _pop_expired_locked(self, now: float) -> list:
        out = []
        for sig in list(self._buckets):
            q = self._buckets[sig]
            keep, dead = collections.deque(), []
            for p in q:
                if p.deadline is not None and p.deadline <= now:
                    dead.append(p)
                else:
                    keep.append(p)
            if dead:
                out.extend(dead)
                if keep:
                    self._buckets[sig] = keep
                else:
                    del self._buckets[sig]
        self._depth -= len(out)
        return out

    def _pop_ready_locked(self, now: float, drain: bool = False):
        """Of the buckets that are full or whose oldest member aged past
        max_delay, the one with the OLDEST head dispatches first — never
        the first-inserted: a sustained hot signature keeps its bucket
        position while non-empty, and insertion-order service would
        starve every other bucket into timeout. Pops up to max_batch.
        While draining, every non-empty bucket is ready — nothing new
        can arrive, so aging a partial batch only delays shutdown."""
        pick = None
        for sig, q in self._buckets.items():
            if (drain or len(q) >= self.max_batch
                    or q[0].enqueued + self.max_delay <= now):
                if pick is None or q[0].enqueued < \
                        self._buckets[pick][0].enqueued:
                    pick = sig
        if pick is None:
            return None, None
        q = self._buckets[pick]
        batch = [q.popleft() for _ in range(min(len(q), self.max_batch))]
        if not q:
            del self._buckets[pick]
        self._depth -= len(batch)
        return pick, batch

    def _wake_in_locked(self, now: float) -> Optional[float]:
        """Sleep until the earliest dispatch-or-deadline event."""
        wake = None
        for q in self._buckets.values():
            t = q[0].enqueued + self.max_delay
            wake = t if wake is None else min(wake, t)
            for p in q:
                if p.deadline is not None:
                    wake = min(wake, p.deadline)
        return None if wake is None else max(0.0, wake - now)

    # -- bookkeeping --------------------------------------------------- #

    def _reject(self, p: Pending, exc: BaseException) -> None:
        if self.registry is not None:
            # queue_full is counted at the door (submit), not here.
            reason = (exc.code if isinstance(exc, Rejected) else "error")
            if reason != "queue_full":
                self.registry.counter("serve_rejected_total",
                                      reason=reason)
        try:
            p.fail(exc)
        except Exception:   # a broken callback must not stall the loop
            pass

    def _gauge_depth(self) -> None:
        if self.registry is not None:
            self.registry.gauge("serve_queue_depth", self.depth())

    def _record_batch(self, sig, batch) -> None:
        now = time.monotonic()
        if tracing.enabled():
            # the queue-wait span, retro-stamped admission -> dispatch
            # (begun on the submitting thread, known-finished here on
            # the scheduler thread — tracing.emit covers that shape)
            for p in batch:
                tracing.emit("serve.queue", p.enqueued, now,
                             kind="queue", parent=request_trace(p.req),
                             signature=str(sig))
        r = self.registry
        if r is None:
            return
        r.counter("serve_dispatch_total")
        r.observe("serve_batch_occupancy", len(batch))
        r.observe("serve_batch_fill", len(batch) / self.max_batch)
        for p in batch:
            r.observe("serve_queue_wait_s", now - p.enqueued)
