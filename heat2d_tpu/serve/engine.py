"""Serve-side ensemble engine: one bucket -> one ``run_ensemble`` launch.

A dispatched bucket is a list of same-signature requests whose (cx, cy)
pairs differ — exactly the heterogeneous-params batch the ensemble
runners were built for. This module turns the bucket into one launch:

- **Warm executables.** The runner comes from
  ``models.ensemble.batch_runner``, the per-signature compile cache: the
  same jitted callable is reused for every launch of a signature, so
  steady-state traffic never retraces (the one-shot entry points rebuild
  ``jax.jit(partial(...))`` per call and retrace every time).
- **Padded batch shapes.** jax re-specializes per batch size; a server
  seeing occupancies 1..max_batch would compile up to max_batch
  programs per signature. Launches pad the member axis up to the next
  power of two (capped at ``max_batch``), replicating the last member's
  (cx, cy) — an inert duplicate that cannot slow a convergence loop
  beyond its twin — and crop on return, so a signature compiles
  O(log max_batch) programs, once each.

Metrics: ``serve_launches_total`` counter, ``serve_launch_s`` histogram,
``serve_compile_cache_info`` gauges (hits/misses of the runner cache).
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import List, Tuple

from heat2d_tpu.resil import chaos

log = logging.getLogger("heat2d_tpu.serve")


def _pad_capacity(n: int, cap: int) -> int:
    """Next power of two >= n, capped at ``cap`` (cap wins even when it
    is not itself a power of two — the bucket never exceeds max_batch)."""
    p = 1
    while p < n:
        p *= 2
    return min(p, cap)


class EnsembleEngine:
    """Executes buckets through the batched ensemble runners. Holds no
    queue state of its own — the batcher owns scheduling; this owns the
    numerics and the launch accounting."""

    def __init__(self, registry=None, max_batch: int = 8,
                 spatial_grid=None, halo: str = "collective"):
        """``spatial_grid``/``halo``: deployment-level decomposition for
        engines serving members bigger than one device (the pod-serving
        direction, ROADMAP item 1): when set, every signature's halo
        route (collective vs fused, tier, depth — incl. the tuning db's
        fused entry) is pre-resolved alongside the tuned band config.
        The plan is ADVISORY today — solve_batch still launches the
        single-device batch runner, so the plan rides launch records
        with ``compiled: False``; the mesh-aware engine (ROADMAP item
        1) flips it when the spatial program actually compiles. None
        (default): no halo plan resolved — behavior byte-identical to
        engines built before the fused route existed."""
        self.registry = registry
        self.max_batch = max_batch
        self.spatial_grid = spatial_grid
        self.halo = halo
        self.launches = 0           # total ensemble launches performed
        self.launch_log: List[dict] = []   # one row per launch (tests)
        #: signatures that have launched at least once in THIS process
        #: — a signature's first launch pays the jit compile, so the
        #: launch row (and the tracing span built from it) flags it:
        #: the trace CLI attributes first-launch time to "compile".
        self._launched: set = set()
        #: signature -> tuned-config dict (or None) resolved BEFORE the
        #: signature's first compile — warmup provenance for the
        #: per-signature compile cache (docs/TUNING.md).
        self.tuned: dict = {}
        #: signature -> pre-resolved halo-route plan (spatial engines
        #: only; see models.ensemble.spatial_halo_plan).
        self.halo_plans: dict = {}

    def _preresolve_tuned(self, req0):
        """Resolve the tuning db's answer for this signature once,
        before its first launch compiles. The band kernels consult the
        same hook during tracing (ops._resolve_bands); resolving here
        makes the answer part of the launch record — a serve deployment
        can see which signatures run measured configs — and warms the
        db lookup off the dispatch path."""
        sig = req0.signature()
        if sig in self.tuned:
            return self.tuned[sig]
        tuned = None
        from heat2d_tpu.models import ensemble
        # Tuned band configs are measured on the heat5 kernels; other
        # families run the registry's generic runners, whose tuning
        # entries live under their own problem-prefixed keys
        # (tune/space.py) and are resolved inside _resolve_bands.
        if (getattr(req0, "problem", "heat5") == "heat5"
                and ensemble._pick_method(req0.method, req0.nx, req0.ny)
                == "band" and not self._window_route(req0)):
            from heat2d_tpu.tune import runtime as tune_runtime
            # allow_window=False: the batched runner's LEGACY band
            # kernel is what consumes the tuned bm (through
            # ops._resolve_bands) — a C2-stamped entry is relabeled
            # route C so the record describes the program that
            # actually compiles (review r6).
            cfg = tune_runtime.band_config(req0.nx, req0.ny, "float32",
                                           allow_window=False)
            if cfg is not None:
                tuned = cfg.to_dict()
        self.tuned[sig] = tuned
        if self.spatial_grid is not None:
            # Fused-route twin of the band-config resolve: the halo
            # plan (route/tier/depth, incl. a tuning-db fused entry) is
            # decided per signature before its first compile, exactly
            # like every other tuned plan (docs/SCALING.md).
            # compiled=False is load-bearing: today's launches are
            # single-device batch runners — the record must not claim
            # a mesh program ran (review: provenance describes the
            # program that actually compiles).
            gx, gy = self.spatial_grid
            self.halo_plans[sig] = dict(
                ensemble.spatial_halo_plan(req0.nx, req0.ny, gx, gy,
                                           halo=self.halo),
                compiled=False)
        if self.registry is not None:
            self.registry.counter("tune_serve_signatures_total",
                                  tuned=str(tuned is not None).lower())
        return tuned

    @staticmethod
    def _window_route(req0) -> bool:
        """True when the batched band runner would take the
        _ens_plan_window route — that branch plans from its own probed
        batched envelope and never consults the tuning db, so claiming
        a tuned config there would misreport the compiled program."""
        import jax.numpy as jnp

        from heat2d_tpu.models import ensemble
        from heat2d_tpu.ops import pallas_stencil as ps
        t = ps.DEFAULT_TSTEPS
        if not (ps._on_tpu() and req0.ny % 128 == 0 and t % 8 == 0):
            return False
        plan = ensemble._ens_plan_window(req0.nx, req0.ny, t,
                                         jnp.float32)
        if plan is None:
            return False
        if not req0.convergence:
            return True
        # Convergence additionally gates on a viable fused-resid band
        # (_band_conv_runner): without one it falls back to the legacy
        # band runner, which DOES consult the db.
        bm, m_pad = plan
        return ensemble._ens_resid_bm(
            m_pad, bm, req0.ny * jnp.dtype(jnp.float32).itemsize,
            t) is not None

    def solve_batch(self, requests) -> List[Tuple["object", int]]:
        """Solve same-signature ``requests`` in ONE ensemble launch.
        Returns one (u, steps_done) pair per request, in order.

        May raise transients (including injected ``ChaosError`` — the
        fault-injection point for the whole launch path); the server's
        retry policy owns absorbing them, this module stays one-shot."""
        chaos.launch_point()
        import numpy as np

        from heat2d_tpu.models import ensemble

        req0 = requests[0]
        tuned = self._preresolve_tuned(req0)
        n = len(requests)
        capacity = _pad_capacity(n, self.max_batch)
        cxs = [r.cx for r in requests]
        cys = [r.cy for r in requests]
        # Pad members replicate the LAST real member: bitwise the same
        # trajectory as their twin, so a convergence launch's while_loop
        # exits exactly when the unpadded batch would.
        cxs += [cxs[-1]] * (capacity - n)
        cys += [cys[-1]] * (capacity - n)

        cxs, cys, u0 = ensemble._validated_batch(
            req0.nx, req0.ny, cxs, cys, None)
        # Canonical schedule: fixed-step requests hand batch_runner
        # (0, 0.0), never their unused interval/sensitivity, so one
        # signature maps to exactly one memoized runner.
        interval, sensitivity = req0.schedule()
        problem = getattr(req0, "problem", "heat5")
        runner = ensemble.batch_runner(
            req0.nx, req0.ny, req0.steps, req0.method,
            convergence=req0.convergence, interval=interval,
            sensitivity=sensitivity, problem=problem)

        timer = (self.registry.timer("serve_launch_s")
                 if self.registry is not None else contextlib.nullcontext())
        t0 = time.monotonic()
        with timer:
            out = runner(u0, cxs, cys)
            if req0.convergence:
                u, steps_done = out
                u = np.asarray(u)
                steps_done = [int(k) for k in np.asarray(steps_done)]
            else:
                u = np.asarray(out)
                steps_done = [req0.steps] * capacity
        elapsed = time.monotonic() - t0

        self.launches += 1
        # per (signature, capacity): the padded ladder compiles one
        # program per capacity, so a known signature at a NEW capacity
        # still pays a compile
        compile_key = (req0.signature(), capacity)
        first_launch = compile_key not in self._launched
        self._launched.add(compile_key)
        row = {"signature": req0.signature(), "occupancy": n,
               "capacity": capacity, "tuned_config": tuned,
               "first_launch": first_launch}
        if self.spatial_grid is not None:
            row["halo_plan"] = self.halo_plans.get(req0.signature())
        # Roofline accounting on EVERY launch row (cheap host math);
        # cost-card extraction only when the perf observer is armed —
        # a dict hit after the first launch per (signature, capacity).
        from heat2d_tpu.obs import perf, roofline
        card = None
        if perf.enabled():
            card = perf.observe_launch(
                runner, (u0, cxs, cys),
                meta={"signature": str(req0.signature()),
                      "nx": req0.nx, "ny": req0.ny,
                      "steps": req0.steps, "method": req0.method,
                      "convergence": req0.convergence,
                      "capacity": capacity, "dtype": "float32",
                      "problem": problem,
                      "route": "batch"})
        roofline.stamp_launch_row(
            row, self.registry, nx=req0.nx, ny=req0.ny,
            steps=(sum(steps_done) / len(steps_done)
                   if req0.convergence else req0.steps),
            members=capacity, elapsed_s=elapsed, method=req0.method,
            signature=str(req0.signature()), card=card,
            problem=problem)
        self.launch_log.append(row)
        if self.registry is not None:
            self.registry.counter("serve_launches_total")
            self.registry.counter("problem_requests_total",
                                  problem=problem)
            self.registry.gauge("serve_compile_cache_size",
                                ensemble.batch_runner.cache_info().currsize)
        log.debug("launch %d: %dx%d steps=%d occupancy=%d/%d",
                  self.launches, req0.nx, req0.ny, req0.steps, n,
                  capacity)
        return [(u[i], steps_done[i]) for i in range(n)]
