from heat2d_tpu.utils.timing import (Stopwatch, TimedCall, timed_call,
                                     max_over_processes)
from heat2d_tpu.utils.device import device_summary

__all__ = ["Stopwatch", "TimedCall", "timed_call", "max_over_processes",
           "device_summary"]
