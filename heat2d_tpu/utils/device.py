"""Device introspection — the detailsGPU analogue
(grad1612_cuda_heat.cu:24-37).

Where the reference printed SM version, memory sizes and warp/block limits,
we report the TPU/host platform facts that matter for this workload: device
kind, count, HBM limits, and the process topology.
"""

from __future__ import annotations

import jax


def device_summary() -> dict:
    devs = jax.devices()
    d0 = devs[0]
    info = {
        "platform": d0.platform,
        "device_kind": getattr(d0, "device_kind", "unknown"),
        "n_devices": len(devs),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "jax_version": jax.__version__,
    }
    try:
        stats = d0.memory_stats()
        if stats:
            info["memory_stats"] = {
                k: stats[k] for k in ("bytes_limit", "bytes_in_use")
                if k in stats}
    except Exception:
        pass
    return info


def print_device_summary() -> None:
    for k, v in device_summary().items():
        print(f"{k}: {v}")
