"""Timing protocol — like-for-like with the reference (SURVEY.md §5.1).

The reference's protocol: barrier, MPI_Wtime, step loop, MPI_Wtime, then
MAX over ranks (grad1612_mpi_heat.c:206-207, 277-280; manual recv-max in
mpi_heat2Dn.c:199-210; cudaEvent pair in grad1612_cuda_heat.cu:79-89).
Setup/compile time is excluded — the clock starts after init, so we
likewise exclude jit compilation by warming up the compiled function
before the timed call.

TPU mapping: the barrier is ``block_until_ready`` on the inputs (plus
``sync_global_devices`` when multi-process); MPI_Wtime is
``time.perf_counter``; the rank-max is a host-side max over processes.
"""

from __future__ import annotations

import time

import jax


def max_over_processes(value: float) -> float:
    """Cluster-max of a host scalar — the MPI_Reduce(MPI_MAX) analogue."""
    if jax.process_count() == 1:
        return float(value)
    from jax.experimental import multihost_utils
    import numpy as np
    gathered = multihost_utils.process_allgather(np.asarray(value))
    return float(gathered.max())


class Stopwatch:
    """Barrier-fenced wall-clock span."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        return False


def _fence(tree) -> None:
    """Hard completion fence: force a tiny host readback from every output.

    ``block_until_ready`` alone is not a reliable fence on every backend
    (remote-tunneled runtimes can acknowledge queued dispatches as ready);
    a 4-byte scalar D2H cannot complete before the producing computation
    has. This is the cudaEventSynchronize analogue
    (grad1612_cuda_heat.cu:87) with teeth.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    import jax.numpy as jnp
    # A reduction to a replicated scalar works for sharded and unsharded
    # leaves alike; its HBM pass is negligible next to any timed run.
    probes = [jnp.sum(leaf) if getattr(leaf, "ndim", 0) else leaf
              for leaf in leaves]
    jax.device_get(probes)


class TimedCall(tuple):
    """``(outputs, elapsed_seconds)`` — unpacks exactly like the 2-tuple
    every existing call site expects — with the setup cost the reference's
    clock placement excludes carried as an attribute instead of discarded:

    - ``warmup_s``: wall-clock of the untimed priming execution
      (compile + program load + first-run transfer), or None when the
      caller skipped the warmup. A first-class metric now (the run
      record and --metrics-out surface it); previously measured nowhere.
    """

    warmup_s: float | None = None

    @property
    def out(self):
        return self[0]

    @property
    def elapsed(self) -> float:
        return self[1]


def timed_call(fn, *args, warmup: bool = True):
    """Run ``fn(*args)`` with the reference's timing protocol.

    Returns a ``TimedCall`` — an ``(outputs, elapsed_seconds)`` 2-tuple
    whose ``warmup_s`` attribute carries the compile/warmup wall-clock.
    ``warmup=True`` runs once first so compilation (the analogue of MPI
    setup, excluded by the reference's clock placement) is not measured.
    """
    warmup_s = None
    if warmup:
        # Warm up by *executing*, not just AOT-compiling: first execution
        # also pays program load / remote-device transfer, which belongs to
        # setup (the reference starts its clock after init). AOT compile
        # alone leaves that cost inside the timed region (measured: 15x
        # inflation through the remote-TPU tunnel).
        w0 = time.perf_counter()
        _fence(fn(*args))
        warmup_s = time.perf_counter() - w0
    for a in args:
        jax.block_until_ready(a)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("heat2d timing barrier")
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    _fence(out)
    elapsed = time.perf_counter() - t0
    result = TimedCall((out, max_over_processes(elapsed)))
    result.warmup_s = warmup_s
    return result
