"""Profiler hooks — the mpiP analogue (SURVEY.md §5.1).

The reference's authors audited *where time goes* with the mpiP link-time
profiler (Report.pdf p.34-37: per-rank AppTime/MPITime and per-callsite
aggregate shares — File_open 29%, Waitall 21% at toy size). mpiP hooks in
via PMPI interposition with zero source changes; the TPU equivalent is
``jax.profiler.trace``, which captures XLA device traces (kernel timeline,
collective ops, transfer costs) viewable in Perfetto/XProf/TensorBoard —
per-op time shares instead of per-MPI-callsite shares.

Usage: ``heat2d-tpu --profile /tmp/trace ...`` wraps the timed run; the
resulting directory is loadable with ``tensorboard --logdir`` or at
ui.perfetto.dev.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def profile_span(logdir: str | None):
    """Trace the enclosed span to ``logdir`` (no-op when logdir is None)."""
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Named sub-span inside a trace (per-phase attribution, e.g. 'halo'
    vs 'stencil' — the per-callsite flavor of the mpiP tables)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
