"""Profiler hooks — the mpiP analogue (SURVEY.md §5.1).

The reference's authors audited *where time goes* with the mpiP link-time
profiler (Report.pdf p.34-37: per-rank AppTime/MPITime and per-callsite
aggregate shares — File_open 29%, Waitall 21% at toy size). mpiP hooks in
via PMPI interposition with zero source changes; the TPU equivalent is
``jax.profiler.trace``, which captures XLA device traces (kernel timeline,
collective ops, transfer costs) viewable in Perfetto/XProf/TensorBoard —
per-op time shares instead of per-MPI-callsite shares.

Usage: ``heat2d-tpu --profile /tmp/trace ...`` wraps the timed run; the
resulting directory is loadable with ``tensorboard --logdir`` or at
ui.perfetto.dev.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def profile_span(logdir: str | None):
    """Trace the enclosed span to ``logdir`` (no-op when logdir is None)."""
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Named sub-span inside a trace (per-phase attribution, e.g. 'halo'
    vs 'stencil' — the per-callsite flavor of the mpiP tables)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def phase(name: str):
    """Phase attribution usable INSIDE jit-traced code.

    ``jax.named_scope`` prefixes the scope name onto every HLO op traced
    under it, so XProf/Perfetto group the op timeline by phase (halo
    exchange vs interior stencil vs residual reduction — the per-callsite
    flavor of the reference's mpiP tables, Report.pdf p.35-37) and
    ``heat2d-tpu-prof`` can attribute them. Metadata only: the compiled
    computation is unchanged, so annotated hot paths cost nothing.
    ``TraceAnnotation`` additionally marks the span when entered outside
    a trace (eager host-side phases).

    When distributed tracing is armed (obs/tracing.py), each entry
    additionally emits a host-side ``phase.<name>`` span — inside
    jit-traced code that stamps TRACE time (i.e. compile-side phase
    attribution), outside it wall time. Pure host bookkeeping either
    way: the traced program is byte-identical with tracing on or off
    (tests/test_tracing.py pins the jaxpr)."""
    import jax

    from heat2d_tpu.obs import tracing

    span = (tracing.begin("phase." + name, kind="phase",
                          parent=tracing.ambient())
            if tracing.enabled() else tracing.NULL_SPAN)
    try:
        with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
            yield
    finally:
        span.end()
