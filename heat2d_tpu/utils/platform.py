"""Host-platform steering for multi-device runs without TPU pods.

The TPU answer to the reference's "multi-node without owning a cluster"
problem (SURVEY.md §4): run the real shard_map/ppermute program on N
virtual CPU devices via --xla_force_host_platform_device_count.

Gotcha this module exists for: the image's sitecustomize imports jax at
interpreter startup pinned to the TPU plugin, so setting JAX_PLATFORMS in
the environment is NOT enough — the live ``jax.config`` must be updated
too, and only before the backend initializes.
"""

from __future__ import annotations

import os
import re

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=(\d+)")


def set_host_device_count(n: int) -> None:
    """Raise the XLA host-platform device count to ``n``.

    Never shrinks a larger pre-set count — another consumer in this
    process may need it. Only affects the *host* (CPU) platform, and only
    if set before the jax backend initializes.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = _COUNT_RE.search(flags)
    if m is None:
        flags = f"{flags} --xla_force_host_platform_device_count={n}".strip()
    elif int(m.group(1)) < n:
        flags = _COUNT_RE.sub(
            f"--xla_force_host_platform_device_count={n}", flags)
    os.environ["XLA_FLAGS"] = flags


def force_host_devices(n: int, platform: str = "cpu") -> None:
    """Steer this process to >= ``n`` virtual host devices on ``platform``.

    ``set_host_device_count(n)`` plus a live jax platform switch. Must run
    before the jax backend initializes; afterwards the platform switch is
    a silent no-op (callers should verify len(jax.devices()) themselves).
    """
    set_host_device_count(n)
    os.environ["JAX_PLATFORMS"] = platform

    import jax

    try:
        jax.config.update("jax_platforms", platform)
    except Exception:
        pass  # backend already up; caller's device-count check will catch it
