"""Mesh degradation — quarantine-driven shrink-and-requeue, and the
ABFT verify tier's host half.

``mesh/health.py`` detects (probes, the stall watchdog); this module
decides and recovers. The contract, in the order the engine runs it:

1. **A launch fails on a device** (``DeviceLostError`` / accelerator
   runtime error), **stalls** (``MeshStallError`` from the watchdog),
   or **fails its ABFT check** (``CorruptionError``).
2. The culprit is quarantined: the named device on a device loss, the
   checksum-mismatching members' OWNER devices on corruption, the
   probe sweep's casualties on a stall (a hang names nobody — the
   probes do). Results of the failed attempt are NEVER served.
3. The batch mesh is RE-FORMED over the surviving devices: the padded
   capacity re-pads to the new device multiple (``mesh_capacity``
   already takes the device count, so the O(log max_batch) compile
   ladder holds per mesh shape) and the SAME batch relaunches — the
   in-flight members ride their existing single-flight futures, so
   followers coalesced onto the leader are requeued for free, exactly
   like the fleet router's failover replay one layer up.
4. Recovery is MEASURED: every requeue episode records cause,
   casualty set, and detect->recover wall seconds into the degrader's
   event log (the run record's ``mesh_fault`` block) and the
   ``mesh_recovery_s`` histogram.

The requeue budget (``FaultPolicy.max_requeues``) bounds the loop;
past it the failure propagates structurally — ``Rejected("mesh_stall")``
for stalls, the original error otherwise — and the server's
retry/breaker plumbing takes over.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Tuple

from heat2d_tpu.mesh.health import HealthMonitor, guarded_call

#: requeue causes (the ``mesh_requeue_total{cause}`` label vocabulary)
REQUEUE_CAUSES = ("device_fail", "mesh_stall", "silent_corruption")


@dataclasses.dataclass(frozen=True)
class FaultPolicy:
    """Opt-in mesh fault tolerance. Everything off by default — an
    engine built without a policy is byte-identical to PR 13's."""

    #: hung-collective deadline (seconds on ``clock``); None = no
    #: stall watchdog (and no per-launch helper thread)
    stall_deadline_s: Optional[float] = None
    #: ABFT checksum verify tier (ops/abft.py) on the batch route
    abft: bool = False
    #: tolerance multiplier (ops/abft.tolerance ``factor``)
    abft_tol_factor: float = 64.0
    #: shrink-and-requeue attempts per launch before the failure
    #: propagates structurally
    max_requeues: int = 2
    #: probe the survivors after a stall to find the casualty
    probe_on_stall: bool = True

    def __post_init__(self):
        if self.max_requeues < 0:
            raise ValueError(
                f"max_requeues must be >= 0, got {self.max_requeues}")
        if (self.stall_deadline_s is not None
                and self.stall_deadline_s <= 0):
            raise ValueError(
                f"stall_deadline_s must be > 0, got "
                f"{self.stall_deadline_s}")


class CorruptionError(RuntimeError):
    """An ABFT checksum mismatch — silent data corruption caught
    before serving. Carries the mismatching member indices and their
    owner devices."""

    def __init__(self, members: List[int], devices: List[int]):
        super().__init__(
            f"ABFT checksum mismatch on members {members} "
            f"(devices {devices})")
        self.members = members
        self.devices = devices


def member_owner(member: int, capacity: int,
                 devices: Tuple[int, ...]) -> int:
    """The device that computed ``member`` of a ``capacity``-padded
    batch sharded ``P('batch')`` over ``devices`` — contiguous equal
    chunks in mesh order (the NamedSharding layout)."""
    per = capacity // len(devices)
    return devices[member // per]


class MeshDegrader:
    """Per-engine fault orchestration state (module docstring)."""

    def __init__(self, policy: FaultPolicy, monitor: HealthMonitor,
                 registry=None, clock=None):
        self.policy = policy
        self.monitor = monitor
        self.registry = registry
        #: the stall watchdog's clock (injectable; None = wall)
        self.clock = clock
        #: one row per recovery episode: cause, devices quarantined,
        #: measured seconds from detection to the recovered launch —
        #: the run record's proof that recovery happened and how fast
        self.events: List[dict] = []

    def now(self) -> float:
        """The fault stack's ONE clock: the injected clock when a test
        froze time, wall monotonic otherwise — detection stamps and
        recovery rows live in the same domain as the stall deadline."""
        return (self.clock or time.monotonic)()

    # -- the guarded launch -------------------------------------------- #

    def guarded(self, fn: Callable[[], object]):
        """Run one launch attempt under the stall watchdog."""
        return guarded_call(fn, self.policy.stall_deadline_s,
                            clock=self.clock,
                            on_discard=self._count_discard)

    def _count_discard(self) -> None:
        if self.registry is not None:
            self.registry.counter("mesh_discarded_results_total",
                                  cause="mesh_stall")

    # -- failure classification ---------------------------------------- #

    def on_device_lost(self, exc: BaseException) -> List[int]:
        """Quarantine after a device-loss failure: the named device
        when the error carries one, else whatever the probe sweep
        convicts. Returns the newly quarantined set."""
        index = getattr(exc, "device_index", None)
        if index is not None:
            self.monitor.quarantine(index, "device_fail")
            return [index]
        failed = [i for i, ok in self.monitor.probe().items() if not ok]
        return failed

    def on_stall(self) -> List[int]:
        """Quarantine after a stall verdict: a hang names nobody, so
        the probe sweep does (``probe_on_stall``), convicting under
        the stall's own reason label."""
        if self.registry is not None:
            self.registry.counter("mesh_stall_total")
        if not self.policy.probe_on_stall:
            return []
        return [i for i, ok in
                self.monitor.probe(reason="mesh_stall").items()
                if not ok]

    def on_corruption(self, exc: CorruptionError) -> List[int]:
        for d in exc.devices:
            self.monitor.quarantine(d, "silent_corruption")
        return list(exc.devices)

    # -- accounting ---------------------------------------------------- #

    def record_requeue(self, cause: str) -> None:
        if cause not in REQUEUE_CAUSES:
            raise ValueError(f"unknown requeue cause {cause!r}")
        if self.registry is not None:
            self.registry.counter("mesh_requeue_total", cause=cause)

    def record_recovery(self, cause: str, casualties: List[int],
                        t_detect: float, devices: Tuple[int, ...],
                        requeues: int) -> dict:
        """Close a recovery episode (called when the relaunch
        SUCCEEDED): wall seconds are measured detect -> now, never
        scheduled."""
        row = {"cause": cause, "quarantined": sorted(casualties),
               "recovery_s": self.now() - t_detect,
               "devices": list(devices), "requeues": requeues}
        self.events.append(row)
        if self.registry is not None:
            self.registry.observe("mesh_recovery_s", row["recovery_s"])
        return row

    def snapshot(self) -> dict:
        """Run-record ``mesh_fault`` block."""
        return {"policy": dataclasses.asdict(self.policy),
                "recoveries": [dict(r) for r in self.events],
                "health": self.monitor.snapshot()}


def serving_invariant(monitor: HealthMonitor,
                      launch_log: List[dict]) -> dict:
    """``no_quarantined_serving``: every SERVED mesh launch ran on a
    device set disjoint from everything quarantined before that
    launch picked its devices (rows carry the monitor's event ``seq``
    fence captured at selection time — a pure ordering check, no
    clock races). The structural twin of the control plane's
    ``no_unvalidated_serving``.

    Parole-aware: a device's status at a launch's fence is decided by
    the LATEST health event at or before the fence — a conviction is
    a violation, a ``kind="readmit"`` parole row clears it. A device
    re-convicted after its parole violates again for later launches,
    so the invariant stays provable through the whole quarantine →
    parole → (maybe re-quarantine) lifecycle."""
    violations = []
    events = monitor.snapshot()["events"]
    for row in launch_log:
        mesh = row.get("mesh") or {}
        devices = mesh.get("devices")
        seq = mesh.get("health_seq")
        if devices is None or seq is None:
            continue
        # events are appended in seq order: last write <= fence wins
        status = {}
        for ev in events:
            if ev["seq"] <= seq:
                status[ev["device"]] = ev
        for d in devices:
            ev = status.get(d)
            if ev is not None and ev.get("kind") != "readmit":
                violations.append({"launch": row.get("signature"),
                                   "device": d,
                                   "event": dict(ev)})
    return {"ok": not violations, "checked": len(launch_log),
            "violations": violations}
