"""The mesh-sharded batch runner — ``ensemble.batch_runner``'s twin
with the padded member axis sharded over every attached chip.

The GSPMD pattern (SNIPPETS.md [2]/[3]): build a named 1D mesh over
the devices, place the ``(B, nx, ny)`` batch (and the per-member
diffusivity vectors) with ``NamedSharding(P('batch'))``, and jit ONE
program — each device advances its local members through the same
single-chip kernel paths (``shard_map`` over the batch axis, so the
Pallas routes work unchanged; the batch axis has no cross-member math
to collectivize on the fixed-step paths, and convergence early-exit
stays device-local exactly like ``run_ensemble_sharded``).

Two contracts carry over from the single-chip runner, both tested:

- **Bitwise parity.** Per-member trajectories are independent of
  batch composition (the property the single-chip padding design
  already relies on: a pad member is an inert replica), so the mesh
  runner's cropped results are bitwise-identical to the single-chip
  ``batch_runner``'s at every occupancy rung.
- **The compile ladder.** Capacities pad to the next power of two AND
  to a device multiple (an uneven batch axis cannot shard), so a
  signature compiles one program per distinct capacity in
  ``{nd, 2*nd, 4*nd, ...} ∩ [nd, max_batch]`` — at most
  ``log2(max_batch) + 1`` programs, the same O(log max_batch) bound
  the recompile sentinel gates (``analysis/recompile.py``).
"""

from __future__ import annotations

import functools
from typing import Optional


def attached_devices(n_devices: Optional[int] = None) -> list:
    """The first ``n_devices`` attached devices (all, when None)."""
    import jax

    devices = list(jax.devices())
    return devices[:n_devices] if n_devices else devices


def mesh_capacity(n: int, max_batch: int, n_devices: int) -> int:
    """Padded launch capacity for ``n`` members on an ``n_devices``
    mesh: next power of two >= n, rounded up to a device multiple (an
    uneven batch axis cannot shard; the mesh always holds at least one
    member per device — inert replicas, like every pad), capped at the
    largest device multiple <= ``max_batch`` (``MeshEnsembleEngine``
    keeps its max_batch a device multiple, so the cap never undercuts
    a bucket)."""
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    cap = max_batch - max_batch % n_devices or n_devices
    p = 1
    while p < n:
        p *= 2
    p = -(-p // n_devices) * n_devices     # device multiple
    return max(min(p, cap), -(-n // n_devices) * n_devices)


@functools.lru_cache(maxsize=128)
def mesh_batch_runner(nx: int, ny: int, steps: int, method: str = "auto",
                      convergence: bool = False, interval: int = 20,
                      sensitivity: float = 0.1,
                      n_devices: Optional[int] = None,
                      device_indices: Optional[tuple] = None,
                      abft: bool = False, problem: str = "heat5"):
    """The per-(signature, mesh) COMPILE-CACHED mesh-sharded runner: a
    ``(u0, cxs, cys) -> batch`` (fixed-step) or ``-> (batch,
    steps_done)`` (convergence) callable whose batch axis is sharded
    ``NamedSharding(P('batch'))`` over the first ``n_devices`` attached
    devices. Memoized like ``ensemble.batch_runner`` so steady-state
    traffic on a warm signature never retraces; callers pad the batch
    to a ``mesh_capacity`` (a device multiple) before launching.

    ``device_indices`` (a sorted tuple of attached-device ordinals)
    builds the mesh over an ARBITRARY device subset instead — the
    quarantine path's shrunken mesh (``mesh/degrade.py``): after a
    device is quarantined the survivors are generally not a prefix, so
    counting alone cannot name them. Wins over ``n_devices`` when
    given; each subset is its own cache entry (its own compile ladder
    per mesh shape).

    ``problem`` names the spatial-operator family (heat2d_tpu/
    problems/): "heat5" (default) shards the pre-registry runners
    byte-identically (jaxpr-pinned); other families shard the
    registry's generic route runners — the batch axis carries whole
    members either way, so the shard_map wrap is family-independent.

    ``abft=True`` arms the checksum verify tier (ops/abft.py): the
    runner additionally returns per-member ``(steps_done, s_obs,
    s_pred, scale)`` — the on-device observation, closed-form
    prediction, and tolerance scale, all member-local (the batch axis
    shards whole members, so no extra collective). A separate cache
    entry: the default program stays byte-identical (jaxpr-pinned).

    The returned callable exposes ``n_devices`` / ``method`` /
    ``device_indices`` / ``abft`` for launch-record provenance.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from heat2d_tpu.models import ensemble
    from heat2d_tpu.parallel.mesh import shard_map_compat

    if problem != "heat5":
        from heat2d_tpu.problems import runners as prunners
        from heat2d_tpu.problems.base import spec_for
        if abft and not spec_for(problem).abft:
            raise ValueError(
                f"problem {problem!r} declares no ABFT recurrence "
                f"(problems/base.py) — gate with spec_for(...).abft "
                f"before arming the runner")
        method = prunners.pick_route(problem, method, nx, ny)
        base = prunners.fixed_runner(problem, method)
    else:
        method = ensemble._pick_method(method, nx, ny)
        base = None
    if device_indices is not None:
        pool = attached_devices(None)
        devices = [pool[i] for i in device_indices]
    else:
        devices = attached_devices(n_devices)
    nd = len(devices)
    mesh = Mesh(np.asarray(devices), ("batch",))
    if base is not None:
        # Generic-family local runner: the same chunked convergence
        # loop the single-chip batch_runner composes (runner-agnostic).
        if convergence:
            local = functools.partial(
                ensemble._run_batch_conv_kernel, steps=steps,
                interval=interval, sensitivity=sensitivity,
                runner=base)
        else:
            local = functools.partial(base, steps=steps)
    elif convergence:
        local = ensemble._conv_runner(method, steps, interval,
                                      sensitivity)
    else:
        local = functools.partial(ensemble._BATCH_RUNNERS[method],
                                  steps=steps)
    if abft:
        local = _abft_wrap(local, nx, ny, steps, method, convergence)
    mapped = shard_map_compat(local, mesh, in_specs=P("batch"),
                              out_specs=P("batch"), check_vma=False)
    # A stable name, like batch_runner's: compile logs / the recompile
    # sentinel attribute every mesh compile to this runner (host-side
    # metadata only — the traced program is unchanged).
    try:
        mapped.__name__ = (f"mesh_batch_runner_{method}"
                           if problem == "heat5" else
                           f"mesh_batch_runner_{problem}_{method}")
    except (AttributeError, TypeError):
        pass
    jitted = jax.jit(mapped)
    sharding = NamedSharding(mesh, P("batch"))

    def run(u0, cxs, cys):
        if u0.shape[0] % nd:
            raise ValueError(
                f"mesh batch axis {u0.shape[0]} is not a multiple of "
                f"the {nd}-device mesh — pad with mesh_capacity first")
        u0 = jax.device_put(u0, sharding)
        cxs = jax.device_put(cxs, sharding)
        cys = jax.device_put(cys, sharding)
        return jitted(u0, cxs, cys)

    run.n_devices = nd
    run.method = method
    run.device_indices = device_indices
    run.abft = abft
    run.problem = problem
    run.jitted = jitted      # the traced program (jaxpr pins)
    return run


def _abft_wrap(local, nx: int, ny: int, steps: int, method: str,
               convergence: bool):
    """Wrap a per-shard batch runner with the ABFT verify tier's
    on-device half (ops/abft.py): one weighted reduction over the
    inputs (prediction + scale) and one over the outputs (observation)
    per member — the ~1%-overhead checksum the engine's host half
    re-checks against the buffer it actually serves."""
    import jax.numpy as jnp

    from heat2d_tpu.ops import abft

    family = abft.supported_family(method)
    if family is None:
        raise ValueError(
            f"method {method!r} has no ABFT recurrence — gate with "
            f"abft.supported_family before arming the runner")
    w = jnp.asarray(abft.mode_weights(nx, ny), jnp.float32)

    def run_verified(u0, cxs, cys):
        out = local(u0, cxs, cys)
        if convergence:
            u, k = out
        else:
            u = out
            k = jnp.full((u.shape[0],), steps, jnp.int32)
        s_pred, scale = abft.predict_batch(u0, cxs, cys, k, w,
                                           family=family)
        s_obs = abft.observe_batch(u, w)
        return u, k, s_obs, s_pred, scale

    return run_verified
