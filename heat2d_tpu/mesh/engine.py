"""``MeshEnsembleEngine`` — the mesh-aware serve engine.

Drop-in for ``serve.engine.EnsembleEngine`` (the server takes either
through its ``engine=`` socket): same ``solve_batch`` contract, same
launch accounting, but each bucket routes through the mesh scheduler:

- **batch** buckets launch the mesh-sharded runner
  (``mesh/runner.py``) at a device-multiple capacity — the padded
  ensemble axis sharded ``P('batch')`` over every chip;
- **spatial** buckets launch the memoized batch x spatial program
  (``ensemble.spatial_batch_runner``) through the fused-halo route —
  and the signature's pre-resolved halo plan (PR 7's
  ``compiled: False`` socket) is finally stamped ``compiled: True``
  with the mesh shape, because the mesh program really built;
- **single** buckets (1-device processes, non-solve kinds,
  ``tier="unplannable"`` shapes) fall through to the inherited
  single-chip path with a ``mesh_fallback_total{reason}`` counter —
  served, never rejected (the totality contract).

Results are bitwise-identical to the single-chip engine's on every
route and every occupancy rung — per-member trajectories are
independent of batch composition and of where the members sit (the
correctness anchor the CI ``mesh-serve-gate`` asserts; the spatial
route's fused-vs-collective bitwise equality is PR 7's proven
contract).

**Fault tolerance** (opt-in via ``fault=FaultPolicy(...)`` —
docs/RESILIENCE.md failure model, CI ``mesh-chaos-gate``): batch
launches run under the hung-collective watchdog (``mesh/health.py``),
device losses / stalls / ABFT checksum mismatches quarantine the
culprit and SHRINK-AND-REQUEUE the same batch over the surviving
devices (capacities re-pad to the new device multiple, in-flight
members ride their existing single-flight futures), spatial-route
signatures degrade onto the survivor batch mesh byte-identically, and
no result from a failed attempt — late, lost, or corrupt — is ever
served (``mesh/degrade.serving_invariant``). Without a policy the
engine is byte-identical to PR 13's.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from heat2d_tpu.mesh.health import MeshStallError
from heat2d_tpu.resil import chaos
from heat2d_tpu.serve.engine import EnsembleEngine
from heat2d_tpu.serve.schema import Rejected


class MeshEnsembleEngine(EnsembleEngine):
    """Mesh-aware ensemble engine (module docstring).

    ``max_batch`` is the TOTAL per-launch bound; it defaults to
    ``max_batch_per_chip * n_devices`` (more chips amortize bigger
    buckets — callers with a per-chip budget, e.g. the CLIs'
    ``--max-batch``, pass it as ``max_batch_per_chip`` so the
    operator's bound scales with the mesh instead of being silently
    replaced) and is rounded up to a device multiple so
    ``mesh_capacity``'s cap can never undercut a full bucket.
    ``scheduler`` defaults to a ``MeshScheduler`` over the same
    devices; pass one explicitly to share its demand window with a
    router."""

    def __init__(self, registry=None, max_batch: Optional[int] = None,
                 n_devices: Optional[int] = None, halo: str = "fused",
                 scheduler=None, max_batch_per_chip: int = 8,
                 fault=None, fault_clock=None):
        """``fault``: a ``degrade.FaultPolicy`` arming quarantine /
        stall-watchdog / ABFT (None = PR 13 behavior, byte-identical).
        ``fault_clock``: the stall deadline's clock (injectable —
        ``resil.retry.Watchdog`` convention; None = wall)."""
        from heat2d_tpu.mesh.runner import attached_devices
        from heat2d_tpu.mesh.scheduler import MeshScheduler

        nd = len(attached_devices(n_devices))
        self.health = None
        self.degrader = None
        if fault is not None:
            import time

            from heat2d_tpu.mesh.degrade import MeshDegrader
            from heat2d_tpu.mesh.health import HealthMonitor
            self.health = HealthMonitor(
                n_devices=nd, registry=registry,
                clock=fault_clock or time.monotonic)
            self.degrader = MeshDegrader(fault, self.health,
                                         registry=registry,
                                         clock=fault_clock)
        if max_batch is None:
            max_batch = max(1, max_batch_per_chip) * nd
        max_batch = -(-max_batch // nd) * nd
        self.scheduler = (scheduler if scheduler is not None
                          else MeshScheduler(n_devices=nd,
                                             registry=registry,
                                             halo=halo))
        self.n_devices = nd
        # spatial_grid arms the inherited per-signature halo-plan
        # pre-resolve (EnsembleEngine._preresolve_tuned) on multi-chip
        # meshes; this engine flips the stamp when the spatial program
        # actually compiles.
        super().__init__(
            registry=registry, max_batch=max_batch,
            spatial_grid=(self.scheduler.spatial_grid()
                          if nd > 1 else None),
            halo=halo)
        #: signature -> memoized spatial runner (built on first
        #: spatial launch; the build IS the mesh compile)
        self._spatial_runners: dict = {}
        #: (signature, capacity, devices, abft) keys that have
        #: launched successfully — the stall watchdog guards only
        #: these WARM launches: a cold launch is dominated by its XLA
        #: compile (host-side work a hung collective cannot stall, but
        #: a deadline tuned for warm execution would spuriously trip),
        #: and stays bounded by the server's launch_deadline watchdog
        #: one layer up.
        self._mesh_warm: set = set()
        #: (elapsed, effective steps, cost card) of the launch attempt
        #: that is about to be accounted — set by the launch paths,
        #: consumed (popped) by ``_account``'s roofline stamp. Engine
        #: calls are serialized by the dispatcher (the same assumption
        #: ``_tag_launch``'s launch_log[-1] already makes).
        self._launch_perf: Optional[dict] = None
        #: voluntary device-count target (``resize``); None = the full
        #: attached mesh. Orthogonal to quarantine: launches form over
        #: the SURVIVORS truncated to this target.
        self._resize_target: Optional[int] = None
        #: one row per ``resize`` call — the actuation audit trail the
        #: autoscale record carries
        self.resize_log: List[dict] = []

    # -- voluntary resize ---------------------------------------------- #

    def resize(self, n: int) -> dict:
        """Voluntarily resize the serving mesh to ``n`` devices — both
        directions (the generalization of shrink-and-requeue's forced
        shrink). Shrinking is immediate: the next launch forms its
        mesh over the first ``n`` survivors and the capacity ladder
        re-pads to the new device multiple. Growing back (up to the
        attached mesh) is just as immediate — devices were never
        released, only unused. Results stay bitwise-identical on every
        size (the mesh-vs-single parity contract). When a fault policy
        is armed the row carries the health fence at decision time, so
        the resize ordering is auditable against quarantine events."""
        n = int(n)
        if not 1 <= n <= self.n_devices:
            raise ValueError(
                f"resize target must be in [1, {self.n_devices}], "
                f"got {n}")
        prev = (self._resize_target if self._resize_target is not None
                else self.n_devices)
        self._resize_target = None if n == self.n_devices else n
        row = {"from": prev, "to": n,
               "health_seq": (self.health.seq()
                              if self.health is not None else None)}
        self.resize_log.append(row)
        if self.registry is not None:
            self.registry.counter(
                "mesh_resize_total",
                direction=("up" if n > prev
                           else "down" if n < prev else "hold"))
            self.registry.gauge("mesh_target_devices", float(n))
        return row

    def active_devices(self) -> Tuple[int, ...]:
        """The device set the next launch forms its mesh over: the
        quarantine survivors (everything attached, without a fault
        policy) truncated to the voluntary resize target."""
        devs = (self.health.survivors() if self.health is not None
                else tuple(range(self.n_devices)))
        t = self._resize_target
        return devs if t is None else devs[:t]

    # -- dispatch ------------------------------------------------------ #

    def solve_batch(self, requests) -> List[Tuple["object", int]]:
        req0 = requests[0]
        decision = self.scheduler.decide(req0)
        route = decision["route"]
        if (self.health is not None and route == "spatial"
                and self.health.quarantined()):
            # Spatial degrade: the spatial program spans the WHOLE
            # attached mesh, quarantined chips included — re-route the
            # signature onto the survivor batch mesh (bitwise-identical
            # results: the mesh-vs-single parity contract), counted
            # like every other fallback (docs/SCALING.md reasons).
            if self.registry is not None:
                self.registry.counter("mesh_fallback_total",
                                      reason="quarantined")
            decision = dict(decision, route="batch",
                            reason="quarantined")
            route = "batch"
        if route == "spatial" and self._resize_target is not None:
            # Voluntary resize: the spatial program likewise spans the
            # whole attached mesh — while a smaller mesh is the target,
            # the signature rides the (resizable) batch route instead,
            # bitwise-identically (same contract as the quarantine
            # reroute above).
            if self.registry is not None:
                self.registry.counter("mesh_fallback_total",
                                      reason="resized")
            decision = dict(decision, route="batch", reason="resized")
            route = "batch"
        if route == "batch":
            return self._solve_batch_mesh(requests, decision)
        if route == "spatial":
            return self._solve_spatial(requests, decision)
        # single-chip fallback: the inherited path, launch row tagged
        # with the fallback reason — served, never rejected.
        if self.registry is not None:
            self.registry.counter("mesh_fallback_total",
                                  reason=decision.get("reason",
                                                      "unknown"))
        return self._solve_single(requests, decision)

    def _solve_single(self, requests,
                      decision) -> List[Tuple["object", int]]:
        """The inherited single-chip launch — quarantine-aware when a
        fault policy is armed: the default device (where an unpinned
        jit computes) may be exactly the convicted chip, so the launch
        is PINNED to the first surviving device and the row stamps
        devices + the health fence like every guarded batch launch —
        ``serving_invariant`` covers this route too, instead of
        skipping it for want of a device set."""
        if self.health is None:
            out = super().solve_batch(requests)
            self._tag_launch(decision)
            return out
        seq = self.health.seq()
        survivors = self.health.survivors()
        if not survivors:
            raise Rejected(
                "mesh_degraded",
                "every device in the mesh is quarantined",
                quarantined=list(self.health.quarantined()))
        import jax

        with jax.default_device(jax.devices()[survivors[0]]):
            out = super().solve_batch(requests)
        self._tag_launch(decision)
        mesh_row = self.launch_log[-1]["mesh"]
        mesh_row["devices"] = [survivors[0]]
        mesh_row["health_seq"] = seq
        return out

    def _tag_launch(self, decision, capacity=None) -> None:
        row = self.launch_log[-1]
        row["mesh"] = {"route": decision["route"],
                       "reason": decision.get("reason"),
                       "n_devices": self.n_devices}
        if capacity is not None:
            row["mesh"]["capacity"] = capacity
        if self.registry is not None:
            self.registry.counter("mesh_launches_total",
                                  route=decision["route"])

    # -- batch-axis route ---------------------------------------------- #

    def _solve_batch_mesh(self, requests,
                          decision) -> List[Tuple["object", int]]:
        chaos.launch_point()
        req0 = requests[0]
        tuned = self._preresolve_tuned(req0)
        n = len(requests)
        if self.degrader is None:
            # voluntary resize applies on the unguarded route too: an
            # explicit device subset when a target is set, the full
            # attached mesh (the byte-identical PR 13 path) otherwise
            active = self.active_devices()
            subset = (None if len(active) == self.n_devices
                      else active)
            u, steps_done, capacity, _ab = self._launch_batch(
                requests, subset, False)
            self._account(req0, n, capacity, tuned, decision,
                          devices=subset)
            return [(u[i], steps_done[i]) for i in range(n)]
        return self._solve_batch_guarded(requests, decision, tuned)

    def _launch_batch(self, requests, device_indices,
                      abft: bool):
        """ONE mesh-sharded launch attempt over ``device_indices``
        (None = the full attached mesh) — pure launch, no accounting.
        Returns ``(u, steps_done, capacity, abft_block)`` with the
        batch PADDED to capacity (the verify tier checks pads too —
        they ran on the same devices)."""
        chaos.mesh_launch_point()
        import contextlib

        import numpy as np

        from heat2d_tpu.mesh.runner import (mesh_batch_runner,
                                            mesh_capacity)
        from heat2d_tpu.models import ensemble

        req0 = requests[0]
        n = len(requests)
        nd = (self.n_devices if device_indices is None
              else len(device_indices))
        capacity = mesh_capacity(n, self.max_batch, nd)
        cxs = [r.cx for r in requests]
        cys = [r.cy for r in requests]
        # Pad members replicate the LAST real member (the single-chip
        # padding contract: an inert twin, bitwise the same trajectory)
        # up to a device-multiple capacity so the batch axis shards.
        cxs += [cxs[-1]] * (capacity - n)
        cys += [cys[-1]] * (capacity - n)
        cxs, cys, u0 = ensemble._validated_batch(
            req0.nx, req0.ny, cxs, cys, None)
        interval, sensitivity = req0.schedule()
        problem = getattr(req0, "problem", "heat5")
        runner = mesh_batch_runner(
            req0.nx, req0.ny, req0.steps, req0.method,
            convergence=req0.convergence, interval=interval,
            sensitivity=sensitivity,
            n_devices=(None if device_indices is not None
                       else self.n_devices),
            device_indices=device_indices, abft=abft,
            problem=problem)
        timer = (self.registry.timer("serve_launch_s")
                 if self.registry is not None
                 else contextlib.nullcontext())
        ab = None
        t0 = time.monotonic()
        with timer:
            out = runner(u0, cxs, cys)
            if abft:
                u, k, s_obs, s_pred, scale = out
                u = np.asarray(u)
                steps_done = [int(x) for x in np.asarray(k)]
                ab = {"s_obs": np.asarray(s_obs),
                      "s_pred": np.asarray(s_pred),
                      "scale": np.asarray(scale)}
            elif req0.convergence:
                u, steps_done = out
                u = np.asarray(u)
                steps_done = [int(k) for k in np.asarray(steps_done)]
            else:
                u = np.asarray(out)
                steps_done = [req0.steps] * capacity
        elapsed = time.monotonic() - t0
        from heat2d_tpu.obs import perf
        card = None
        if perf.enabled():
            card = perf.observe_launch(
                runner, (u0, cxs, cys),
                meta={"signature": str(req0.signature()),
                      "nx": req0.nx, "ny": req0.ny,
                      "steps": req0.steps, "method": req0.method,
                      "convergence": req0.convergence,
                      "capacity": capacity, "dtype": "float32",
                      "problem": problem,
                      "route": "mesh_batch"})
        self._launch_perf = {
            "elapsed_s": elapsed,
            "steps": (sum(steps_done) / len(steps_done)
                      if req0.convergence else req0.steps),
            "card": card}
        return u, steps_done, capacity, ab

    # -- the guarded (fault-tolerant) batch route ---------------------- #

    def _solve_batch_guarded(self, requests, decision,
                             tuned) -> List[Tuple["object", int]]:
        """Shrink-and-requeue launch loop (module docstring): each
        attempt runs on the CURRENT survivors under the stall
        watchdog; device losses / stalls / checksum mismatches
        quarantine the culprit and relaunch the same batch over the
        shrunken mesh, re-padded to its device multiple. The members'
        single-flight futures upstream never see the churn — requeue
        is invisible except in the measured recovery row."""
        import numpy as np

        from heat2d_tpu.mesh.degrade import CorruptionError
        from heat2d_tpu.mesh.health import is_device_loss
        from heat2d_tpu.models import ensemble
        from heat2d_tpu.ops import abft as abft_lib

        policy = self.degrader.policy
        req0 = requests[0]
        n = len(requests)
        problem = getattr(req0, "problem", "heat5")
        if problem == "heat5":
            method = ensemble._pick_method(req0.method, req0.nx,
                                           req0.ny)
            abft_armed = (policy.abft
                          and abft_lib.supported_family(method)
                          is not None)
            unsupported_reason = method
        else:
            # The ABFT checksum recurrence is derived for the heat5
            # operator; families declare abft=False (problems/base.py)
            # and serve unverified under a fault policy — counted,
            # never crashed (the runner-level gate would raise).
            from heat2d_tpu.problems import runners as prunners
            method = prunners.pick_route(problem, req0.method,
                                         req0.nx, req0.ny)
            abft_armed = False
            unsupported_reason = f"problem_{problem}"
        if (policy.abft and not abft_armed
                and self.registry is not None):
            # opt-in tier, honestly reported: this method has no exact
            # linear recurrence — served unverified, counted
            self.registry.counter("mesh_abft_unsupported_total",
                                  reason=unsupported_reason)
        requeues = 0
        first_cause: Optional[str] = None
        casualties: List[int] = []
        t_detect: Optional[float] = None
        from heat2d_tpu.mesh.runner import mesh_capacity

        while True:
            seq = self.health.seq()
            # survivors truncated to the voluntary resize target
            devices = self.active_devices()
            if not devices:
                raise Rejected(
                    "mesh_degraded",
                    "every device in the mesh is quarantined",
                    quarantined=list(self.health.quarantined()))
            warm_key = (req0.signature(),
                        mesh_capacity(n, self.max_batch, len(devices)),
                        devices, abft_armed)
            launch = (lambda d=devices: self._launch_batch(
                requests, d, abft_armed))
            try:
                if warm_key in self._mesh_warm:
                    u, steps_done, capacity, ab = \
                        self.degrader.guarded(launch)
                else:
                    # cold: the compile dominates — run it unguarded
                    # (see _mesh_warm) so a deadline tuned for warm
                    # execution cannot spuriously convict a fresh mesh
                    u, steps_done, capacity, ab = launch()
                self._mesh_warm.add(warm_key)
                bit = chaos.flip_bit_point()
                if bit is not None:
                    # injected readback corruption: one exponent bit
                    # of member 0's center cell, host-side only (the
                    # traced program is untouched — jaxpr-pinned)
                    u = u.copy()
                    u.view(np.uint32)[0, req0.nx // 2,
                                      req0.ny // 2] ^= np.uint32(
                                          1 << bit)
                if abft_armed:
                    self._abft_verify(req0, u, steps_done, ab,
                                      devices, capacity, policy)
                break
            except BaseException as e:  # noqa: BLE001 — classified
                if isinstance(e, MeshStallError):
                    cause, newly = "mesh_stall", self.degrader.on_stall()
                elif isinstance(e, CorruptionError):
                    cause = "silent_corruption"
                    newly = self.degrader.on_corruption(e)
                elif is_device_loss(e):
                    cause = "device_fail"
                    newly = self.degrader.on_device_lost(e)
                    if not newly:
                        # a runtime error that names no device AND
                        # whose probe sweep convicts nobody is not a
                        # device fault (e.g. a deterministic OOM /
                        # invalid-argument failure): requeueing would
                        # relaunch the same failing program
                        # max_requeues more times per request —
                        # propagate and let the server's transient
                        # classification decide instead
                        raise
                else:
                    raise       # not a device-domain failure
                if t_detect is None:
                    t_detect = self.degrader.now()
                first_cause = first_cause or cause
                casualties.extend(d for d in newly
                                  if d not in casualties)
                if (requeues >= policy.max_requeues
                        or not self.health.survivors()):
                    if cause == "mesh_stall":
                        raise Rejected(
                            "mesh_stall",
                            f"mesh launch stalled past the "
                            f"{policy.stall_deadline_s}s deadline "
                            f"({requeues} requeues spent)",
                            quarantined=list(
                                self.health.quarantined())) from e
                    raise
                requeues += 1
                self.degrader.record_requeue(cause)
        recovery = None
        if first_cause is not None:
            recovery = self.degrader.record_recovery(
                first_cause, casualties, t_detect, devices, requeues)
        self._account(req0, n, capacity, tuned, decision,
                      devices=devices, health_seq=seq,
                      recovery=recovery)
        return [(u[i], steps_done[i]) for i in range(n)]

    def _abft_verify(self, req0, u, steps_done, ab, devices,
                     capacity, policy) -> None:
        """The verify tier's host half: re-derive the checksum from
        the buffer that is ABOUT TO BE SERVED (catching readback /
        host corruption) and cross-check the on-device observation —
        both against the on-device closed-form prediction. A mismatch
        convicts the owning devices and raises ``CorruptionError``
        (the launch loop quarantines and recomputes from the
        digest-verified inputs)."""
        import numpy as np

        from heat2d_tpu.mesh.degrade import CorruptionError, member_owner
        from heat2d_tpu.ops import abft

        s_pred = ab["s_pred"]
        scale = ab["scale"]
        k = np.asarray(steps_done, np.float64)
        f = policy.abft_tol_factor
        bad = (abft.classify(abft.host_checksum(u), s_pred, scale, k,
                             factor=f)
               | abft.classify(ab["s_obs"], s_pred, scale, k,
                               factor=f))
        if self.registry is not None:
            self.registry.counter("mesh_abft_checked_total",
                                  value=float(capacity))
        members = [int(m) for m in np.nonzero(bad)[0]]
        if not members:
            return
        owners = sorted({member_owner(m, capacity, devices)
                         for m in members})
        if self.registry is not None:
            self.registry.counter("mesh_abft_mismatch_total",
                                  value=float(len(members)))
        raise CorruptionError(members, owners)

    # -- spatial route ------------------------------------------------- #

    def _spatial_runner(self, req0, decision):
        from heat2d_tpu.models import ensemble

        sig = req0.signature()
        runner = self._spatial_runners.get(sig)
        if runner is not None:
            return runner
        gx, gy = decision["spatial_grid"]
        interval, sensitivity = req0.schedule()
        runner = ensemble.spatial_batch_runner(
            req0.nx, req0.ny, req0.steps, gx, gy,
            convergence=req0.convergence, interval=interval,
            sensitivity=sensitivity, halo=self.halo,
            n_devices=self.n_devices)
        self._spatial_runners[sig] = runner
        # The PR 7 socket, closed: the plan row finally records that
        # the mesh program actually built (and on what mesh).
        plan = self.halo_plans.get(sig)
        if plan is not None:
            plan["compiled"] = True
            plan["mesh"] = (gx, gy)
            plan["local_batch"] = runner.nb
        if self.registry is not None:
            self.registry.counter("mesh_spatial_compiled_total")
        return runner

    def _solve_spatial(self, requests,
                       decision) -> List[Tuple["object", int]]:
        chaos.launch_point()
        import contextlib

        import numpy as np

        from heat2d_tpu.mesh.runner import mesh_capacity
        from heat2d_tpu.models import ensemble

        req0 = requests[0]
        tuned = self._preresolve_tuned(req0)
        runner = self._spatial_runner(req0, decision)
        n = len(requests)
        # Capacity ladder over the LOCAL batch unit: one spatial wave
        # advances nb members (one per submesh row), so capacities are
        # nb multiples — same O(log max_batch) discipline.
        capacity = mesh_capacity(n, self.max_batch, runner.nb)
        cxs = [r.cx for r in requests]
        cys = [r.cy for r in requests]
        cxs += [cxs[-1]] * (capacity - n)
        cys += [cys[-1]] * (capacity - n)
        cxs, cys, u0 = ensemble._validated_batch(
            req0.nx, req0.ny, cxs, cys, None)

        def launch():
            chaos.mesh_launch_point()
            t0 = time.monotonic()
            u, k = runner(u0, cxs, cys)
            u = np.asarray(u)
            steps_done = [int(s) for s in np.asarray(k)]
            elapsed = time.monotonic() - t0
            from heat2d_tpu.obs import perf
            card = None
            if perf.enabled():
                card = perf.observe_launch(
                    runner, (u0, cxs, cys),
                    meta={"signature": str(req0.signature()),
                          "nx": req0.nx, "ny": req0.ny,
                          "steps": req0.steps, "method": req0.method,
                          "convergence": req0.convergence,
                          "capacity": capacity, "dtype": "float32",
                          "route": "mesh_spatial"})
            self._launch_perf = {
                "elapsed_s": elapsed,
                "steps": sum(steps_done) / len(steps_done),
                "card": card}
            return (u, steps_done)

        timer = (self.registry.timer("serve_launch_s")
                 if self.registry is not None
                 else contextlib.nullcontext())
        if self.degrader is None:
            with timer:
                u, steps_done = launch()
            self._account(req0, n, capacity, tuned, decision)
            return [(u[i], steps_done[i]) for i in range(n)]
        return self._spatial_guarded(requests, decision, tuned,
                                     capacity, launch, timer)

    def _spatial_guarded(self, requests, decision, tuned, capacity,
                         launch, timer) -> List[Tuple["object", int]]:
        """The spatial route's fault tier: the launch runs under the
        stall watchdog (warm launches only — same rationale as the
        batch route) and a device-domain failure is CLASSIFIED, not
        propagated raw: the conviction quarantines the culprit and
        the SAME batch re-dispatches through ``solve_batch``, where
        the quarantine check reroutes it onto the survivor batch mesh
        (bitwise-identical results — the mesh-vs-single parity
        contract). Without this, a chip dying mid-spatial-launch
        fails forever: the server's retry relaunches the identical
        full-mesh program that still includes the dead device."""
        from heat2d_tpu.mesh.health import is_device_loss

        req0 = requests[0]
        n = len(requests)
        warm_key = (req0.signature(), capacity, "spatial")
        try:
            if warm_key in self._mesh_warm:
                with timer:
                    u, steps_done = self.degrader.guarded(launch)
            else:
                # cold: the compile dominates — unguarded (_mesh_warm)
                with timer:
                    u, steps_done = launch()
            self._mesh_warm.add(warm_key)
        except BaseException as e:  # noqa: BLE001 — classified
            t_detect = self.degrader.now()
            if isinstance(e, MeshStallError):
                cause, newly = "mesh_stall", self.degrader.on_stall()
                if not newly:
                    # nobody convicted: re-dispatch would rebuild the
                    # same full-mesh program and hang again —
                    # structural rejection, the server's plumbing
                    # takes over
                    raise Rejected(
                        "mesh_stall",
                        "spatial mesh launch stalled past the "
                        f"{self.degrader.policy.stall_deadline_s}s "
                        "deadline and the probe sweep convicted no "
                        "device") from e
            elif is_device_loss(e):
                cause = "device_fail"
                newly = self.degrader.on_device_lost(e)
                if not newly:
                    raise   # not a device fault (see the batch twin)
            else:
                raise       # not a device-domain failure
            self.degrader.record_requeue(cause)
            # quarantine is non-empty now, so dispatch reroutes this
            # signature onto the survivor batch mesh
            out = self.solve_batch(requests)
            self.degrader.record_recovery(
                cause, newly, t_detect,
                tuple(self.health.survivors()), 1)
            return out
        self._account(req0, n, capacity, tuned, decision)
        return [(u[i], steps_done[i]) for i in range(n)]

    # -- shared accounting --------------------------------------------- #

    def _account(self, req0, n, capacity, tuned, decision,
                 devices=None, health_seq=None,
                 recovery=None) -> None:
        """The inherited launch bookkeeping (launch_log / first_launch
        / serve metrics), shared by both mesh routes. Fault-tolerant
        launches additionally stamp the device set they ACTUALLY ran
        on, the health-event fence captured when that set was chosen
        (``degrade.serving_invariant`` checks served-launch devices
        against quarantines ordered before the fence), and the
        measured recovery row when the launch survived a requeue."""
        self.launches += 1
        compile_key = (req0.signature(), capacity, decision["route"],
                       devices)
        first_launch = compile_key not in self._launched
        self._launched.add(compile_key)
        row = {"signature": req0.signature(), "occupancy": n,
               "capacity": capacity, "tuned_config": tuned,
               "first_launch": first_launch}
        if self.spatial_grid is not None:
            row["halo_plan"] = self.halo_plans.get(req0.signature())
        self.launch_log.append(row)
        if self.registry is not None:
            self.registry.counter("serve_launches_total")
            self.registry.counter(
                "problem_requests_total",
                problem=getattr(req0, "problem", "heat5"))
        self._tag_launch(decision, capacity=capacity)
        if devices is not None:
            mesh_row = self.launch_log[-1]["mesh"]
            mesh_row["devices"] = list(devices)
            mesh_row["health_seq"] = health_seq
            mesh_row["degraded"] = len(devices) < self.n_devices
            if recovery is not None:
                mesh_row["recovery"] = dict(recovery)
        # roofline stamp for the mesh routes (the single-chip fallback
        # is stamped by the inherited solve_batch)
        lp, self._launch_perf = self._launch_perf, None
        if lp is not None:
            from heat2d_tpu.obs import roofline
            roofline.stamp_launch_row(
                row, self.registry, nx=req0.nx, ny=req0.ny,
                steps=lp["steps"], members=capacity,
                elapsed_s=lp["elapsed_s"], method=req0.method,
                signature=str(req0.signature()), card=lp["card"],
                problem=getattr(req0, "problem", "heat5"))

    def fault_snapshot(self) -> Optional[dict]:
        """Run-record ``mesh_fault`` block: policy, measured recovery
        episodes, quarantine book, and the serving invariant verdict
        over this engine's launch log (None without a fault policy)."""
        if self.degrader is None:
            return None
        from heat2d_tpu.mesh.degrade import serving_invariant
        snap = self.degrader.snapshot()
        snap["invariant"] = serving_invariant(self.health,
                                              self.launch_log)
        return snap
