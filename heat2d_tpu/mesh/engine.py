"""``MeshEnsembleEngine`` — the mesh-aware serve engine.

Drop-in for ``serve.engine.EnsembleEngine`` (the server takes either
through its ``engine=`` socket): same ``solve_batch`` contract, same
launch accounting, but each bucket routes through the mesh scheduler:

- **batch** buckets launch the mesh-sharded runner
  (``mesh/runner.py``) at a device-multiple capacity — the padded
  ensemble axis sharded ``P('batch')`` over every chip;
- **spatial** buckets launch the memoized batch x spatial program
  (``ensemble.spatial_batch_runner``) through the fused-halo route —
  and the signature's pre-resolved halo plan (PR 7's
  ``compiled: False`` socket) is finally stamped ``compiled: True``
  with the mesh shape, because the mesh program really built;
- **single** buckets (1-device processes, non-solve kinds,
  ``tier="unplannable"`` shapes) fall through to the inherited
  single-chip path with a ``mesh_fallback_total{reason}`` counter —
  served, never rejected (the totality contract).

Results are bitwise-identical to the single-chip engine's on every
route and every occupancy rung — per-member trajectories are
independent of batch composition and of where the members sit (the
correctness anchor the CI ``mesh-serve-gate`` asserts; the spatial
route's fused-vs-collective bitwise equality is PR 7's proven
contract).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from heat2d_tpu.resil import chaos
from heat2d_tpu.serve.engine import EnsembleEngine


class MeshEnsembleEngine(EnsembleEngine):
    """Mesh-aware ensemble engine (module docstring).

    ``max_batch`` is the TOTAL per-launch bound; it defaults to
    ``max_batch_per_chip * n_devices`` (more chips amortize bigger
    buckets — callers with a per-chip budget, e.g. the CLIs'
    ``--max-batch``, pass it as ``max_batch_per_chip`` so the
    operator's bound scales with the mesh instead of being silently
    replaced) and is rounded up to a device multiple so
    ``mesh_capacity``'s cap can never undercut a full bucket.
    ``scheduler`` defaults to a ``MeshScheduler`` over the same
    devices; pass one explicitly to share its demand window with a
    router."""

    def __init__(self, registry=None, max_batch: Optional[int] = None,
                 n_devices: Optional[int] = None, halo: str = "fused",
                 scheduler=None, max_batch_per_chip: int = 8):
        from heat2d_tpu.mesh.runner import attached_devices
        from heat2d_tpu.mesh.scheduler import MeshScheduler

        nd = len(attached_devices(n_devices))
        if max_batch is None:
            max_batch = max(1, max_batch_per_chip) * nd
        max_batch = -(-max_batch // nd) * nd
        self.scheduler = (scheduler if scheduler is not None
                          else MeshScheduler(n_devices=nd,
                                             registry=registry,
                                             halo=halo))
        self.n_devices = nd
        # spatial_grid arms the inherited per-signature halo-plan
        # pre-resolve (EnsembleEngine._preresolve_tuned) on multi-chip
        # meshes; this engine flips the stamp when the spatial program
        # actually compiles.
        super().__init__(
            registry=registry, max_batch=max_batch,
            spatial_grid=(self.scheduler.spatial_grid()
                          if nd > 1 else None),
            halo=halo)
        #: signature -> memoized spatial runner (built on first
        #: spatial launch; the build IS the mesh compile)
        self._spatial_runners: dict = {}

    # -- dispatch ------------------------------------------------------ #

    def solve_batch(self, requests) -> List[Tuple["object", int]]:
        req0 = requests[0]
        decision = self.scheduler.decide(req0)
        route = decision["route"]
        if route == "batch":
            return self._solve_batch_mesh(requests, decision)
        if route == "spatial":
            return self._solve_spatial(requests, decision)
        # single-chip fallback: the inherited path, launch row tagged
        # with the fallback reason — served, never rejected.
        if self.registry is not None:
            self.registry.counter("mesh_fallback_total",
                                  reason=decision.get("reason",
                                                      "unknown"))
        out = super().solve_batch(requests)
        self._tag_launch(decision)
        return out

    def _tag_launch(self, decision, capacity=None) -> None:
        row = self.launch_log[-1]
        row["mesh"] = {"route": decision["route"],
                       "reason": decision.get("reason"),
                       "n_devices": self.n_devices}
        if capacity is not None:
            row["mesh"]["capacity"] = capacity
        if self.registry is not None:
            self.registry.counter("mesh_launches_total",
                                  route=decision["route"])

    # -- batch-axis route ---------------------------------------------- #

    def _solve_batch_mesh(self, requests,
                          decision) -> List[Tuple["object", int]]:
        chaos.launch_point()
        import contextlib

        import numpy as np

        from heat2d_tpu.mesh.runner import (mesh_batch_runner,
                                            mesh_capacity)
        from heat2d_tpu.models import ensemble

        req0 = requests[0]
        tuned = self._preresolve_tuned(req0)
        n = len(requests)
        capacity = mesh_capacity(n, self.max_batch, self.n_devices)
        cxs = [r.cx for r in requests]
        cys = [r.cy for r in requests]
        # Pad members replicate the LAST real member (the single-chip
        # padding contract: an inert twin, bitwise the same trajectory)
        # up to a device-multiple capacity so the batch axis shards.
        cxs += [cxs[-1]] * (capacity - n)
        cys += [cys[-1]] * (capacity - n)
        cxs, cys, u0 = ensemble._validated_batch(
            req0.nx, req0.ny, cxs, cys, None)
        interval, sensitivity = req0.schedule()
        runner = mesh_batch_runner(
            req0.nx, req0.ny, req0.steps, req0.method,
            convergence=req0.convergence, interval=interval,
            sensitivity=sensitivity, n_devices=self.n_devices)
        timer = (self.registry.timer("serve_launch_s")
                 if self.registry is not None
                 else contextlib.nullcontext())
        with timer:
            out = runner(u0, cxs, cys)
            if req0.convergence:
                u, steps_done = out
                u = np.asarray(u)
                steps_done = [int(k) for k in np.asarray(steps_done)]
            else:
                u = np.asarray(out)
                steps_done = [req0.steps] * capacity
        self._account(req0, n, capacity, tuned, decision)
        return [(u[i], steps_done[i]) for i in range(n)]

    # -- spatial route ------------------------------------------------- #

    def _spatial_runner(self, req0, decision):
        from heat2d_tpu.models import ensemble

        sig = req0.signature()
        runner = self._spatial_runners.get(sig)
        if runner is not None:
            return runner
        gx, gy = decision["spatial_grid"]
        interval, sensitivity = req0.schedule()
        runner = ensemble.spatial_batch_runner(
            req0.nx, req0.ny, req0.steps, gx, gy,
            convergence=req0.convergence, interval=interval,
            sensitivity=sensitivity, halo=self.halo,
            n_devices=self.n_devices)
        self._spatial_runners[sig] = runner
        # The PR 7 socket, closed: the plan row finally records that
        # the mesh program actually built (and on what mesh).
        plan = self.halo_plans.get(sig)
        if plan is not None:
            plan["compiled"] = True
            plan["mesh"] = (gx, gy)
            plan["local_batch"] = runner.nb
        if self.registry is not None:
            self.registry.counter("mesh_spatial_compiled_total")
        return runner

    def _solve_spatial(self, requests,
                       decision) -> List[Tuple["object", int]]:
        chaos.launch_point()
        import contextlib

        import numpy as np

        from heat2d_tpu.mesh.runner import mesh_capacity
        from heat2d_tpu.models import ensemble

        req0 = requests[0]
        tuned = self._preresolve_tuned(req0)
        runner = self._spatial_runner(req0, decision)
        n = len(requests)
        # Capacity ladder over the LOCAL batch unit: one spatial wave
        # advances nb members (one per submesh row), so capacities are
        # nb multiples — same O(log max_batch) discipline.
        capacity = mesh_capacity(n, self.max_batch, runner.nb)
        cxs = [r.cx for r in requests]
        cys = [r.cy for r in requests]
        cxs += [cxs[-1]] * (capacity - n)
        cys += [cys[-1]] * (capacity - n)
        cxs, cys, u0 = ensemble._validated_batch(
            req0.nx, req0.ny, cxs, cys, None)
        timer = (self.registry.timer("serve_launch_s")
                 if self.registry is not None
                 else contextlib.nullcontext())
        with timer:
            u, k = runner(u0, cxs, cys)
            u = np.asarray(u)
            steps_done = [int(s) for s in np.asarray(k)]
        self._account(req0, n, capacity, tuned, decision)
        return [(u[i], steps_done[i]) for i in range(n)]

    # -- shared accounting --------------------------------------------- #

    def _account(self, req0, n, capacity, tuned, decision) -> None:
        """The inherited launch bookkeeping (launch_log / first_launch
        / serve metrics), shared by both mesh routes."""
        self.launches += 1
        compile_key = (req0.signature(), capacity, decision["route"])
        first_launch = compile_key not in self._launched
        self._launched.add(compile_key)
        row = {"signature": req0.signature(), "occupancy": n,
               "capacity": capacity, "tuned_config": tuned,
               "first_launch": first_launch}
        if self.spatial_grid is not None:
            row["halo_plan"] = self.halo_plans.get(req0.signature())
        self.launch_log.append(row)
        if self.registry is not None:
            self.registry.counter("serve_launches_total")
        self._tag_launch(decision, capacity=capacity)
