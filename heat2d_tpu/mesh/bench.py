"""``bench_serve`` — serve-side strong scaling over the device mesh,
riding ``parallel/scaling.py``'s ``kind="multichip"`` records.

Two numbers per run, with different epistemics, both in the record:

- **Bitwise parity** (the correctness anchor, MEASURED everywhere):
  the mesh engine's results vs the single-chip engine's on every
  occupancy rung 1..max, every tested signature — byte-for-byte.
- **Throughput scaling** — on real hardware (``rate_source="wall"``)
  the wall-clock request rate of full-capacity launches at 1 chip vs
  n chips. On a HOST-SIMULATED mesh (CI's
  ``--xla_force_host_platform_device_count``) the n "chips" share one
  CPU's cores, so wall clock cannot show device scaling; there the
  record carries the MODELED surface (``rate_source="modeled"``) —
  the same resource model the mesh admission control prices work
  with: each chip advances its local members in parallel (batch DP
  has no cross-member dependency), charged a per-launch dispatch
  overhead plus a collective tax on multi-chip meshes. The model's
  parameters are stated in the payload so the gate's 1→8 efficiency
  assertion is auditable — it proves the scheduler's capacity math,
  the compile ladder, and bitwise parity; silicon scaling is
  ``tpu_smoke.py``'s job (the same split the tune subsystem's
  SimulatedBackend made for CPU CI).

    serve_scaling_efficiency = rate_n / (n * rate_1)

``main`` writes the record (``scaling_record``) and exits nonzero when
parity breaks, the efficiency misses ``--min-efficiency``, or a
spatial signature fails to stamp its halo plan ``compiled: True`` —
the CI ``mesh-serve-gate``'s teeth.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Optional

#: the modeled-surface constants (stated in every payload)
SERVE_SCALING_MODEL = "heat2d-tpu/serve-scaling-model/v1"
MODEL_LAUNCH_OVERHEAD_S = 1e-3
MODEL_COLLECTIVE_TAX_S = 2e-4
MODEL_PER_CHIP_MCELLS_PER_S = 1000.0


def modeled_launch_s(member_cells: float, capacity: int,
                     n_devices: int,
                     per_chip_cells_per_s: float) -> float:
    """Modeled wall time of one full-capacity launch: per-chip local
    members advance in parallel; multi-chip meshes pay a collective
    tax (dispatch + the batch axis's gather)."""
    local = -(-capacity // n_devices)
    t = MODEL_LAUNCH_OVERHEAD_S + local * member_cells \
        / per_chip_cells_per_s
    if n_devices > 1:
        t += MODEL_COLLECTIVE_TAX_S
    return t


def _reqs(nx, ny, steps, n, method="jnp", base=0.05):
    from heat2d_tpu.serve.schema import SolveRequest

    return [SolveRequest(nx=nx, ny=ny, steps=steps, method=method,
                         cx=base + 0.01 * i, cy=0.1) for i in range(n)]


def _parity_rungs(mesh_engine, single_engine, nx, ny, steps,
                  method, rungs) -> list:
    """Serve every occupancy rung through BOTH engines; byte-compare
    each member. Returns the per-rung report (all must be True)."""
    import numpy as np

    out = []
    for n in rungs:
        reqs = _reqs(nx, ny, steps, n, method=method,
                     base=0.05 + 0.001 * n)
        got = mesh_engine.solve_batch(reqs)
        want = single_engine.solve_batch(reqs)
        ok = all(
            np.asarray(g[0]).tobytes() == np.asarray(w[0]).tobytes()
            and g[1] == w[1]
            for g, w in zip(got, want))
        out.append({"occupancy": n, "bitwise": bool(ok)})
    return out


def _wall_rate(engine, nx, ny, steps, method, capacity,
               launches: int = 3) -> float:
    """Measured requests/s of warm full-capacity launches."""
    reqs = _reqs(nx, ny, steps, capacity, method=method, base=0.3)
    engine.solve_batch(reqs)                   # warm (compile)
    t0 = time.monotonic()
    for i in range(launches):
        engine.solve_batch(_reqs(nx, ny, steps, capacity,
                                 method=method, base=0.4 + 0.01 * i))
    dt = max(time.monotonic() - t0, 1e-9)
    return launches * capacity / dt


def measure_serve_scaling(n_devices: Optional[int] = None,
                          nx: int = 48, ny: int = 64, steps: int = 8,
                          method: str = "jnp",
                          per_chip_mcells_per_s: Optional[float] = None,
                          wall: bool = True) -> dict:
    """One serve strong-scaling measurement (module docstring).
    Returns the ``kind="multichip"`` payload row."""
    import jax

    from heat2d_tpu.mesh.engine import MeshEnsembleEngine
    from heat2d_tpu.mesh.scheduler import tuned_rate_mcells
    from heat2d_tpu.serve.engine import EnsembleEngine

    nd = n_devices or len(jax.devices())
    single = EnsembleEngine(max_batch=8)
    meshed = MeshEnsembleEngine(n_devices=nd)
    rungs = sorted({1, 2, 3, 5, 8})
    parity = _parity_rungs(meshed, single, nx, ny, steps, method,
                           rungs)
    cap_1, cap_n = 8, meshed.max_batch
    on_tpu = jax.devices()[0].platform == "tpu"
    rate = (per_chip_mcells_per_s
            or tuned_rate_mcells(nx, ny)
            or MODEL_PER_CHIP_MCELLS_PER_S)
    cells = float(nx) * ny * steps
    m1 = cap_1 / modeled_launch_s(cells, cap_1, 1, rate * 1e6)
    mn = cap_n / modeled_launch_s(cells, cap_n, nd, rate * 1e6)
    payload = {
        "bench": "serve",
        "n_devices": nd,
        "grid": [nx, ny], "steps": steps, "method": method,
        "max_batch_1chip": cap_1, "max_batch_nchip": cap_n,
        "parity": all(r["bitwise"] for r in parity),
        "parity_rungs": parity,
        "rate_source": "wall" if on_tpu else "modeled",
        "model": {
            "name": SERVE_SCALING_MODEL,
            "per_chip_mcells_per_s": rate,
            "launch_overhead_s": MODEL_LAUNCH_OVERHEAD_S,
            "collective_tax_s": MODEL_COLLECTIVE_TAX_S,
        },
        "modeled_rps_1chip": m1,
        "modeled_rps_nchip": mn,
        "modeled_scaling_efficiency": mn / (nd * m1),
    }
    if wall:
        w1 = _wall_rate(single, nx, ny, steps, method, cap_1)
        wn = _wall_rate(meshed, nx, ny, steps, method, cap_n)
        payload.update(wall_rps_1chip=w1, wall_rps_nchip=wn,
                       wall_scaling_efficiency=wn / (nd * w1))
    eff_key = ("wall_scaling_efficiency" if on_tpu
               else "modeled_scaling_efficiency")
    payload["serve_scaling_efficiency"] = payload[eff_key]
    return payload


def measure_spatial_serve(n_devices: Optional[int] = None,
                          nx: int = 48, ny: int = 64,
                          steps: int = 8) -> dict:
    """Serve one spatial-routed signature through the mesh engine
    (the split forced via a 1-byte threshold so the leg runs on CI
    grids) and prove the PR 7 socket closed: the halo plan stamps
    ``compiled: True`` with the mesh shape, and the spatial results
    are bitwise the single-chip engine's."""
    import jax
    import numpy as np

    from heat2d_tpu.mesh.engine import MeshEnsembleEngine
    from heat2d_tpu.mesh.scheduler import MeshScheduler
    from heat2d_tpu.serve.engine import EnsembleEngine

    nd = n_devices or len(jax.devices())
    if nd < 2:
        return {"bench": "serve_spatial", "skipped": "one_device"}
    sched = MeshScheduler(n_devices=nd, spatial_bytes_threshold=1)
    meshed = MeshEnsembleEngine(n_devices=nd, scheduler=sched)
    single = EnsembleEngine(max_batch=8)
    reqs = _reqs(nx, ny, steps, 3, base=0.07)
    got = meshed.solve_batch(reqs)
    want = single.solve_batch(reqs)
    parity = all(
        np.asarray(g[0]).tobytes() == np.asarray(w[0]).tobytes()
        for g, w in zip(got, want))
    sig = reqs[0].signature()
    plan = meshed.halo_plans.get(sig) or {}
    decision = meshed.scheduler.decide(reqs[0])
    return {
        "bench": "serve_spatial",
        "n_devices": nd, "grid": [nx, ny], "steps": steps,
        "route": decision["route"],
        "parity": bool(parity),
        "halo_plan": {k: (list(v) if isinstance(v, tuple) else v)
                      for k, v in plan.items()},
        "compiled": bool(plan.get("compiled")),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-mesh",
        description="bench_serve: mesh-serving strong scaling + "
                    "bitwise parity gate (docs/SCALING.md)")
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--nx", type=int, default=48)
    p.add_argument("--ny", type=int, default=64)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--method", default="jnp")
    p.add_argument("--min-efficiency", type=float, default=0.75,
                   help="gate: serve_scaling_efficiency floor "
                        "(0.75 at 8 chips == 6x)")
    p.add_argument("--no-spatial", action="store_true",
                   help="skip the spatial-route leg")
    p.add_argument("--no-wall", action="store_true",
                   help="skip wall-clock rates (parity + model only)")
    p.add_argument("--out", default=None, metavar="JSON",
                   help="write the kind='multichip' run record here")
    args = p.parse_args(argv)

    from heat2d_tpu.parallel.scaling import scaling_record

    failures = []
    payloads = [measure_serve_scaling(
        n_devices=args.devices, nx=args.nx, ny=args.ny,
        steps=args.steps, method=args.method, wall=not args.no_wall)]
    row = payloads[0]
    print(f"bench_serve: {row['n_devices']} devices, parity="
          f"{row['parity']}, {row['rate_source']} efficiency "
          f"{row['serve_scaling_efficiency']:.3f} "
          f"({row['serve_scaling_efficiency'] * row['n_devices']:.1f}x"
          f" at {row['n_devices']} chips)")
    if not row["parity"]:
        failures.append(f"mesh-vs-single-chip parity broke: "
                        f"{row['parity_rungs']}")
    if row["serve_scaling_efficiency"] < args.min_efficiency:
        failures.append(
            f"serve scaling efficiency "
            f"{row['serve_scaling_efficiency']:.3f} < "
            f"--min-efficiency {args.min_efficiency}")
    if not args.no_spatial:
        sp = measure_spatial_serve(n_devices=args.devices,
                                   nx=args.nx, ny=args.ny,
                                   steps=args.steps)
        payloads.append(sp)
        if sp.get("skipped"):
            print(f"bench_serve spatial: SKIP ({sp['skipped']})")
        else:
            print(f"bench_serve spatial: route={sp['route']} "
                  f"compiled={sp['compiled']} parity={sp['parity']}")
            if not sp["parity"]:
                failures.append("spatial route parity broke")
            if sp["route"] != "spatial" or not sp["compiled"]:
                failures.append(
                    "spatial signature did not compile a mesh "
                    f"program: {sp}")
    scaling_record(payloads, args.out)
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    print("bench_serve " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
