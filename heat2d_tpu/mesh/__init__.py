"""Pod-scale serving — the mesh scheduler subsystem (ROADMAP item 1).

The serve stack built in PRs 2-12 launches every bucket on effectively
one replica group: ``EnsembleEngine`` dispatches through the
single-device batch runners while 7 of the 8 attached chips idle (the
MULTICHIP rounds prove they are green), and PR 7 left the spatial
socket wired but dark — ``ensemble.spatial_halo_plan`` pre-resolves a
route/tier/depth per serve signature and stamps every plan
``compiled: False`` "until the mesh-aware engine lands". This package
is that engine, mesh-aware along BOTH axes:

- ``runner``    — the mesh-sharded batch runner: a named 1D mesh over
                  all attached devices, ``NamedSharding(P('batch'))``
                  on the padded ensemble axis (the GSPMD pattern —
                  SNIPPETS.md [2]/[3]), capacities padded to device
                  multiples so the O(log max_batch) compile ladder
                  survives the mesh.
- ``scheduler`` — the batch-vs-spatial split per signature bucket from
                  a resource model (member grid bytes vs per-chip
                  VMEM, demand from the per-signature counters, tuned
                  rates from the tune db), plus ``MeshAdmission`` —
                  shedding on MODELED mesh saturation, not queue depth
                  alone.
- ``engine``    — ``MeshEnsembleEngine``: routes each bucket to the
                  mesh batch runner, the spatial fused-halo runner
                  (finally flipping the halo plan to
                  ``compiled: True``), or the single-chip path
                  (``tier="unplannable"`` shapes fall back with a
                  ``mesh_fallback_total{reason}`` counter instead of
                  rejecting) — bitwise-identical results to the
                  single-chip engine on every route.
- ``bench``     — ``bench_serve`` strong scaling riding
                  ``parallel/scaling.py``'s ``kind="multichip"``
                  records, with mesh-vs-single-chip bitwise parity as
                  the correctness anchor (the CI ``mesh-serve-gate``).
- ``health``    — the device-level failure domain's detection half:
                  per-device probes, the quarantine book, and the
                  hung-collective watchdog
                  (``resil.retry.Watchdog(clock=)``) that bounds a
                  stalled mesh launch.
- ``degrade``   — quarantine-driven recovery: shrink-and-requeue over
                  the surviving devices, the ABFT checksum verify
                  tier's policy (``ops/abft.py`` holds the algebra),
                  measured recovery rows, and the
                  no-quarantined-serving invariant (the CI
                  ``mesh-chaos-gate``).
- ``chaos_gate``— the three measured device-fault scenarios (device
                  loss, silent bit flip, hung collective), each
                  recovering to a bitwise-correct answer on the
                  8-device sim mesh.

Everything is opt-in: a ``SolveServer`` built without a mesh engine is
byte-identical to the PR-2 stack (the jaxpr pins hold with this
package imported, scheduled, and admitted).
"""

from heat2d_tpu.mesh.degrade import FaultPolicy, MeshDegrader
from heat2d_tpu.mesh.engine import MeshEnsembleEngine
from heat2d_tpu.mesh.health import HealthMonitor, MeshStallError
from heat2d_tpu.mesh.runner import mesh_batch_runner, mesh_capacity
from heat2d_tpu.mesh.scheduler import MeshAdmission, MeshScheduler

__all__ = ["FaultPolicy", "HealthMonitor", "MeshAdmission",
           "MeshDegrader", "MeshEnsembleEngine", "MeshScheduler",
           "MeshStallError", "mesh_batch_runner", "mesh_capacity"]
