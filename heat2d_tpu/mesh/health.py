"""Per-device health — probes, quarantine state, and the
hung-collective watchdog.

The fleet subsystem (PR 5) answers PROCESS death and the checkpoint
manager (PR 3) answers STATE loss; neither helps when one *device* in
a live mesh goes bad: a hung ICI collective stalls every in-flight
batch forever (no exception, no exit code — the gray failure), and a
core that "doesn't count" (Hochschild et al., HotOS'21 — PAPERS.md)
corrupts results silently. This module is the device-level failure
domain's detection half (docs/RESILIENCE.md failure-model table):

- ``HealthMonitor`` — the quarantine book: per-device status, reason
  and ordering of every quarantine decision, the surviving-device
  set the mesh engine re-forms its mesh over, and the capacity
  fraction the control plane's sizing advice consumes.
- ``probe_device`` / ``HealthMonitor.probe`` — a tiny place-compute-
  readback round trip per device, verified against its known answer
  (a wrong answer IS a failure — probes cover corrupt cores, not
  just dead ones). The chaos hook ``device_probe_point`` lets a
  campaign kill a specific device deterministically.
- ``guarded_call`` — the hung-collective watchdog: runs a launch on a
  helper thread under ``resil.retry.Watchdog`` (the ONE injectable-
  clock deadline convention) and raises ``MeshStallError`` when the
  deadline passes. The abandoned launch keeps running — Python
  cannot preempt it — but its eventual result is DISCARDED and
  counted (``mesh_discarded_results_total``): no result computed by
  a launch that stalled is ever served, however late it arrives.

Recovery (shrink-and-requeue, ABFT verification) lives in
``mesh/degrade.py``; this module only detects and remembers.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple

from heat2d_tpu.analysis.locks import AuditedLock
from heat2d_tpu.resil import chaos
from heat2d_tpu.resil.retry import wait_for

#: probe payload length — big enough to cross the device boundary,
#: small enough to be free (one cacheline-ish)
PROBE_N = 16

#: per-device probe deadline: a gray-failing device can HANG the
#: place-compute-readback round trip, not just fail it — an unbounded
#: probe would wedge the very sweep the stall watchdog hands off to
PROBE_DEADLINE_S = 5.0

#: quarantine reasons (the ``mesh_quarantine_total{reason}`` label
#: vocabulary — docs/SCALING.md; ``host_lost`` is the whole-host
#: failure domain the dist bridge convicts with, docs/DISTRIBUTED.md)
QUARANTINE_REASONS = ("probe_failure", "device_fail", "mesh_stall",
                     "silent_corruption", "host_lost")

#: consecutive verified probe passes a quarantined device must string
#: together before ``HealthMonitor.parole`` re-admits it — one lucky
#: probe is not evidence of health, N in a row is
PAROLE_PASSES = 3


class MeshStallError(RuntimeError):
    """A mesh launch outlived its stall deadline — the structured form
    of the eternal hang. The engine converts it into quarantine +
    requeue, or ``Rejected("mesh_stall")`` once the requeue budget is
    spent."""


def is_device_loss(exc: BaseException) -> bool:
    """Failures that name a DEVICE as the casualty: the injected
    ``DeviceLostError`` and the accelerator-runtime errors a real
    dead chip raises mid-collective (name-matched like
    ``resil.retry.default_transient`` — the classes move between
    modules across jax versions)."""
    if isinstance(exc, chaos.DeviceLostError):
        return True
    return type(exc).__name__ in ("XlaRuntimeError", "JaxRuntimeError")


def probe_device(index: int) -> bool:
    """One device health probe: place a small iota on the device,
    compute on it, read it back, verify the ANSWER (not just
    liveness). Any exception or wrong answer is a failure."""
    if not chaos.device_probe_point(index):
        return False
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        dev = jax.devices()[index]
        x = jax.device_put(jnp.arange(PROBE_N, dtype=jnp.float32), dev)
        got = np.asarray(x + 1.0)
        want = np.arange(1, PROBE_N + 1, dtype=np.float32)
        return bool(np.array_equal(got, want))
    except Exception:
        return False


class HealthMonitor:
    """The per-mesh quarantine book (module docstring). Thread-safe:
    quarantine decisions arrive from launch paths, watchdog watcher
    threads, and probe sweeps. ``clock`` stamps event rows (injectable
    for deterministic tests; wall monotonic by default)."""

    def __init__(self, n_devices: Optional[int] = None, registry=None,
                 clock: Callable[[], float] = time.monotonic):
        from heat2d_tpu.mesh.runner import attached_devices

        self.n_devices = len(attached_devices(n_devices))
        self.registry = registry
        self.clock = clock
        self._lock = AuditedLock("mesh.health")
        self._quarantined: dict = {}     # device -> event row
        #: every quarantine decision, in order — the audit trail the
        #: serving invariant (mesh/degrade.py) is checked against
        self.events: list = []
        self._seq = 0

    # -- state --------------------------------------------------------- #

    def seq(self) -> int:
        """Event ordinal fence: launches capture it BEFORE choosing
        their device set, so 'quarantined before this launch' is a
        pure integer comparison — no clock races."""
        with self._lock:
            return self._seq

    def is_quarantined(self, index: int) -> bool:
        with self._lock:
            return index in self._quarantined

    def quarantined(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._quarantined))

    def survivors(self) -> Tuple[int, ...]:
        """Device indices the next mesh forms over (may be empty)."""
        with self._lock:
            return tuple(i for i in range(self.n_devices)
                         if i not in self._quarantined)

    def capacity_fraction(self) -> float:
        """Surviving share of the attached mesh — the control plane's
        sizing input (docs/CONTROL.md)."""
        with self._lock:
            live = self.n_devices - len(self._quarantined)
        return live / self.n_devices if self.n_devices else 0.0

    def snapshot(self) -> dict:
        """Run-record block: quarantine set + events + capacity."""
        with self._lock:
            return {"n_devices": self.n_devices,
                    "quarantined": sorted(self._quarantined),
                    "capacity_fraction":
                        (self.n_devices - len(self._quarantined))
                        / self.n_devices if self.n_devices else 0.0,
                    "events": [dict(e) for e in self.events]}

    # -- transitions --------------------------------------------------- #

    def quarantine(self, index: int, reason: str) -> bool:
        """Quarantine ``index`` (idempotent; False = already out).
        Quarantine is one-way by default: a device that failed once
        does not get re-trusted by the layer that caught it —
        re-admission is an operator decision (``parole``, which
        demands consecutive verified probe passes), not a retry."""
        if reason not in QUARANTINE_REASONS:
            raise ValueError(
                f"reason must be one of {QUARANTINE_REASONS}, got "
                f"{reason!r}")
        if not 0 <= index < self.n_devices:
            raise ValueError(
                f"device index {index} outside the "
                f"{self.n_devices}-device mesh")
        with self._lock:
            if index in self._quarantined:
                return False
            self._seq += 1
            row = {"seq": self._seq, "t": self.clock(),
                   "device": index, "reason": reason}
            self._quarantined[index] = row
            self.events.append(row)
            live = self.n_devices - len(self._quarantined)
        if self.registry is not None:
            self.registry.counter("mesh_quarantine_total",
                                  reason=reason)
            self.registry.gauge("mesh_quarantined_devices",
                                float(self.n_devices - live))
        return True

    def probe(self, devices: Optional[Tuple[int, ...]] = None,
              reason: str = "probe_failure") -> dict:
        """Probe ``devices`` (default: current survivors); quarantine
        every failure. ``reason`` labels the conviction — a sweep run
        to attribute a stall convicts as ``mesh_stall``, a routine
        sweep as ``probe_failure`` — so the documented
        ``mesh_quarantine_total{reason}`` vocabulary is reachable
        end to end. Returns {index: ok}."""
        out = {}
        for i in (self.survivors() if devices is None else devices):
            try:
                # bounded: a hung probe convicts like a wrong answer
                # (wall clock deliberately — this bounds a host-side
                # hang; the monitor's clock may be frozen by a test)
                ok = guarded_call(lambda d=i: probe_device(d),
                                  PROBE_DEADLINE_S)
            except MeshStallError:
                ok = False
            out[i] = ok
            if not ok:
                if self.registry is not None:
                    self.registry.counter("mesh_probe_failures_total")
                self.quarantine(i, reason)
        return out

    def parole(self, index: int, passes: int = PAROLE_PASSES,
               probe: Optional[Callable[[int], bool]] = None) -> bool:
        """Re-admit a quarantined device after ``passes`` CONSECUTIVE
        verified probe passes (the operator decision ``quarantine``'s
        docstring defers to — quarantine stays one-way unless somebody
        explicitly asks for parole).

        Each probe is the full place-compute-readback round trip under
        the stall watchdog; ONE failure (or hang) ends the hearing and
        the device stays out. Success appends a seq-fenced ``readmit``
        event — ``kind="readmit"`` — so the serving invariant
        (mesh/degrade.py) stays a pure integer-ordinal question: a
        launch fenced AFTER the readmit may use the device, a launch
        fenced before it may not. Returns True iff re-admitted.
        ``probe`` is injectable for deterministic tests (defaults to
        the real ``probe_device``)."""
        if passes < 1:
            raise ValueError(f"passes must be >= 1, got {passes}")
        if not 0 <= index < self.n_devices:
            raise ValueError(
                f"device index {index} outside the "
                f"{self.n_devices}-device mesh")
        if not self.is_quarantined(index):
            return False            # nothing to parole
        probe_fn = probe_device if probe is None else probe
        for _ in range(passes):
            try:
                ok = guarded_call(lambda: probe_fn(index),
                                  PROBE_DEADLINE_S)
            except MeshStallError:
                ok = False
            if not ok:
                if self.registry is not None:
                    self.registry.counter("mesh_parole_total",
                                          outcome="denied")
                return False
        with self._lock:
            if index not in self._quarantined:
                return False        # a racing parole already won
            self._seq += 1
            row = {"seq": self._seq, "t": self.clock(),
                   "device": index, "reason": "parole",
                   "kind": "readmit", "passes": passes}
            del self._quarantined[index]
            self.events.append(row)
            live = self.n_devices - len(self._quarantined)
        if self.registry is not None:
            self.registry.counter("mesh_parole_total", outcome="paroled")
            self.registry.gauge("mesh_quarantined_devices",
                                float(self.n_devices - live))
        return True


def guarded_call(fn: Callable[[], object],
                 deadline_s: Optional[float], *,
                 clock: Optional[Callable[[], float]] = None,
                 on_discard: Optional[Callable[[], None]] = None,
                 poll: float = 0.005):
    """Run ``fn()`` under the hung-collective watchdog: returns its
    result (or re-raises its exception) when it finishes inside
    ``deadline_s``; raises ``MeshStallError`` when it does not.

    The stalled call keeps running on its (daemon) helper thread —
    the host cannot preempt a wedged collective — but the moment the
    stall verdict lands, its eventual result is marked DISCARDED:
    ``on_discard`` fires when (if) the abandoned call completes, so
    the never-serve-a-stalled-result invariant is observable, not
    just intended. ``deadline_s=None`` degrades to a plain call."""
    if deadline_s is None:
        return fn()

    lock = AuditedLock("mesh.health.guard")
    done = threading.Event()
    box: dict = {}
    state = {"done": False, "discarded": False}

    def run() -> None:
        try:
            value = fn()
            err = None
        except BaseException as e:     # noqa: BLE001 — re-raised below
            value, err = None, e
        with lock:
            box["value"], box["error"] = value, err
            state["done"] = True
            discarded = state["discarded"]
        done.set()
        if discarded and on_discard is not None:
            on_discard()

    t = threading.Thread(target=run, name="heat2d-mesh-launch",
                         daemon=True)
    t.start()
    # the ONE bounded-poll deadline convention (resil.retry.wait_for
    # on Watchdog(clock=)); done.wait doubles as the poll sleep
    wait_for(done.is_set, deadline_s, clock=clock, poll=poll,
             sleep=lambda s: done.wait(s))
    with lock:
        if state["done"]:
            err = box["error"]
            if err is not None:
                raise err
            return box["value"]
        # stall: from here on the launch's result is tainted — flag it
        # BEFORE releasing the lock so the finishing thread cannot race
        # past the verdict
        state["discarded"] = True
    raise MeshStallError(
        f"mesh launch outlived its {deadline_s}s stall deadline")
