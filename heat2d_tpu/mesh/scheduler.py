"""The mesh scheduler — batch-vs-spatial split per signature bucket,
and admission control on MODELED mesh capacity.

Two separable concerns live here, both host-side pure-ish math (no
jax on the decision path — devices are only counted):

- ``MeshScheduler.decide(req0)`` — ONE routing decision per serve
  signature, memoized like every other per-signature pre-resolve
  (tuned band config, halo plan):

  * **batch** — many-small-request traffic: the member fits a chip
    comfortably, so the win is throughput — shard the padded member
    axis over the whole mesh (``mesh/runner.py``).
  * **spatial** — huge-grid traffic: the member's working set exceeds
    the per-chip VMEM envelope (``spatial_bytes_threshold``, default
    the live per-chip VMEM total — past it a single chip must
    band-stream from HBM), so the win is latency — decompose each
    member over a near-square submesh through the proven fused-halo
    route (``spatial_halo_plan``, PR 7's kernel-F/overlap tiers).
  * **single** — everything the mesh cannot take: 1-device processes,
    non-solve request kinds, and ``tier="unplannable"`` shapes (the
    PR 7 totality contract: the plan resolve never fails a request
    the single-chip runner serves fine) — recorded as
    ``mesh_fallback_total{reason}`` and served by the single-chip
    engine, never rejected.

  The decision row carries the tune db's per-device-kind answer
  (measured Mcells/s for the shape, when one exists) and the current
  per-signature demand (a ``CounterDeltas`` window over the serve /
  fleet ``*_signature_requests_total`` families — the same primitive
  the control plane's retuner uses) so launch records and the
  capacity model see WHY a route was picked.

- ``MeshAdmission`` — the breaker sheds on repeated failures and the
  batcher on queue depth; neither knows the mesh is saturated until
  latency collapses. This models it instead: every admitted solve
  charges its cell-update work (``nx * ny * steps`` — the convergence
  budget is an upper bound, conservative the right way) to a sliding
  window, and a leader whose work would push the windowed offered
  rate past ``headroom x`` the modeled mesh capacity (chips x
  per-chip rate, tune-db-informed) is shed with
  ``Rejected("mesh_saturated")`` BEFORE it queues. Cache hits and
  coalesced followers never reach it (they cost no launch), matching
  the breaker's shed-compute-not-answers contract.
"""

from __future__ import annotations

import time
from typing import Optional

from heat2d_tpu.analysis.locks import AuditedLock
from heat2d_tpu.serve.schema import Rejected

#: default per-chip serve rate for the admission model when the tune
#: db holds no measured rate for the device kind — deliberately
#: conservative (a v5e measures ~2.2e5 Mcells/s on the saturated
#: kernel, a CPU worker orders of magnitude less; an overestimate
#: would never shed).
DEFAULT_PER_CHIP_MCELLS_PER_S = 500.0


def grid_bytes(nx: int, ny: int, itemsize: int = 4,
               problem: str = "heat5") -> int:
    """One member's grid bytes — the resource model's unit. Scaled by
    the problem family's declared state-array count (problems/base.py:
    varcoef carries per-cell diffusivity fields beside the state, so a
    member costs 3x the bare grid)."""
    from heat2d_tpu.problems.base import state_arrays

    return int(nx) * int(ny) * itemsize * state_arrays(problem)


def _per_chip_vmem_bytes() -> int:
    """The live per-chip VMEM total the split threshold defaults to
    (the same detection every kernel planner uses)."""
    from heat2d_tpu.ops import pallas_stencil as ps

    return ps._vmem_total()[0]


def tuned_rate_mcells(nx: int, ny: int,
                      dtype: str = "float32") -> Optional[float]:
    """The tune db's measured Mcells/s for this shape on THIS device
    kind (``tune.runtime.measured_rate`` — the same lookup ladder as
    every config consult), or None — the admission model's per-chip
    rate source."""
    from heat2d_tpu.tune import runtime as tune_runtime

    return tune_runtime.measured_rate(nx, ny, dtype)


class MeshScheduler:
    """Per-signature routing decisions over an ``n_devices`` mesh.

    ``demand_source``: optional ``(registry, prefix)`` pair naming the
    per-signature request counters demand is read from (the router's
    ``fleet_signature_requests_total`` fleet-side, the server's
    ``serve_signature_requests_total`` in-process). ``halo`` is the
    spatial route's requested halo (default "fused" — the proven
    overlap route; degradation is the plan's job, not the
    scheduler's). ``world`` is an optional ``dist.runtime.DistWorld``:
    with it, spatial decision rows carry a ``links`` block — the
    DCN/ICI seam census of the submesh the member would decompose
    over and the modeled per-step seam seconds, priced with the same
    link bandwidths depth tuning uses (tune/measure.py) — so launch
    records show when a spatial split would push halo traffic across
    hosts."""

    def __init__(self, n_devices: Optional[int] = None, registry=None,
                 halo: str = "fused",
                 spatial_bytes_threshold: Optional[int] = None,
                 demand_source=None, world=None):
        from heat2d_tpu.mesh.runner import attached_devices
        from heat2d_tpu.obs.metrics import CounterDeltas

        self.n_devices = len(attached_devices(n_devices))
        self.registry = registry
        self.halo = halo
        self.spatial_bytes_threshold = (
            _per_chip_vmem_bytes() if spatial_bytes_threshold is None
            else int(spatial_bytes_threshold))
        self.demand_source = demand_source
        self.world = world
        self._deltas = CounterDeltas()
        self._decisions: dict = {}
        self._lock = AuditedLock("mesh.scheduler")

    # -- demand -------------------------------------------------------- #

    def _demand(self, sig_str: str) -> Optional[float]:
        """Requests seen for this signature since the last decision
        tick (a window, not a cumulative count), or None without a
        demand source."""
        if self.demand_source is None:
            return None
        registry, prefix = self.demand_source
        if registry is None:
            return None
        total = 0.0
        for k, d in self._deltas.tick(
                registry, prefix + "_signature_requests_total").items():
            if dict(k).get("signature") == sig_str:
                total += d
        return total

    # -- the split ----------------------------------------------------- #

    def spatial_grid(self) -> tuple:
        """The near-square submesh each spatial member decomposes
        over — the whole mesh (one member in flight at a time is the
        latency-optimal shape for huge grids)."""
        from heat2d_tpu.parallel.scaling import square_mesh

        return square_mesh(self.n_devices)

    def decide(self, req0) -> dict:
        """The memoized routing decision for ``req0``'s signature."""
        sig = req0.signature()
        with self._lock:
            hit = self._decisions.get(sig)
        if hit is not None:
            return hit
        d = self._decide(req0)
        with self._lock:
            d = self._decisions.setdefault(sig, d)
        if self.registry is not None:
            self.registry.counter("mesh_route_total", route=d["route"])
        return d

    def _decide(self, req0) -> dict:
        problem = getattr(req0, "problem", "heat5")
        bytes_ = grid_bytes(req0.nx, req0.ny, problem=problem)
        out = {
            "signature": str(req0.signature()),
            "n_devices": self.n_devices,
            "member_bytes": bytes_,
            "spatial_bytes_threshold": self.spatial_bytes_threshold,
            "demand": self._demand(str(req0.signature())),
            "tuned_mcells_per_s": tuned_rate_mcells(
                req0.nx, req0.ny, getattr(req0, "dtype", "float32")),
        }
        if getattr(req0, "request_kind", "solve") != "solve":
            return dict(out, route="single", reason="request_kind")
        if self.n_devices < 2:
            return dict(out, route="single", reason="one_device")
        if bytes_ <= self.spatial_bytes_threshold:
            return dict(out, route="batch", reason="fits_chip",
                        spatial_grid=None)
        if problem != "heat5":
            # The spatial decomposition (halo plans, fused kernels) is
            # built on the heat5 stencil; oversized members of other
            # families follow the totality contract — served single-
            # chip (the generic runners band-stream from HBM), never
            # rejected.
            return dict(out, route="single", reason="problem_spatial")
        from heat2d_tpu.models import ensemble

        gx, gy = self.spatial_grid()
        plan = ensemble.spatial_halo_plan(req0.nx, req0.ny, gx, gy,
                                          halo=self.halo)
        if plan.get("tier") == "unplannable":
            # The PR 7 totality contract, followed through: shapes the
            # decomposition cannot take are SERVED (single-chip), not
            # rejected — the fallback is a counter, never an error.
            return dict(out, route="single", reason="unplannable",
                        plan=plan)
        return dict(out, route="spatial", reason="exceeds_chip",
                    spatial_grid=(gx, gy), plan=plan,
                    links=self._seam_links(gx, gy, req0.ny))

    def _seam_links(self, gx: int, gy: int, ny: int) -> Optional[dict]:
        """The spatial row's cross-host seam pricing (class docstring):
        seam census over the (gx, gy) arrangement of the pod's
        host-major device order, plus the modeled seconds one step's
        edge traffic costs on each link class. None without a world
        (the single-host schedulers lose nothing) or when the submesh
        does not cover the pod exactly (no arrangement to census)."""
        if self.world is None:
            return None
        from heat2d_tpu.dist.mesh import arrange_pod, seam_profile
        from heat2d_tpu.tune.measure import link_bytes_per_s

        if gx * gy != self.world.n_devices:
            return None
        prof = seam_profile(self.world, arrange_pod(self.world, gx, gy),
                            ny)
        ici_bytes = (prof["seam_bytes_per_step"]
                     - prof["dcn_bytes_per_step"])
        prof["seam_s_per_step"] = (
            ici_bytes / link_bytes_per_s("ici")
            + prof["dcn_bytes_per_step"] / link_bytes_per_s("dcn"))
        return prof

    def decisions(self) -> dict:
        """signature -> decision row (a copy; run-record provenance)."""
        with self._lock:
            return dict(self._decisions)


class MeshAdmission:
    """Modeled-saturation admission control (module docstring).

    ``clock`` is injectable so shedding scenarios are deterministic on
    any host speed (the ``resil/retry.Watchdog`` pattern)."""

    def __init__(self, n_devices: Optional[int] = None, registry=None,
                 per_chip_mcells_per_s: Optional[float] = None,
                 window_s: float = 2.0, headroom: float = 1.25,
                 clock=None):
        from heat2d_tpu.mesh.runner import attached_devices

        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if headroom <= 0:
            raise ValueError(f"headroom must be > 0, got {headroom}")
        self.n_devices = len(attached_devices(n_devices))
        self.registry = registry
        self.per_chip_mcells_per_s = per_chip_mcells_per_s
        self.window_s = window_s
        self.headroom = headroom
        self.clock = clock if clock is not None else time.monotonic
        self._window: list = []     # (t, cells) of admitted work
        self._lock = AuditedLock("mesh.admission")

    # -- the model ----------------------------------------------------- #

    @staticmethod
    def work_cells(req) -> float:
        """Cell updates one request costs the mesh: nx * ny * steps.
        A convergence run may exit early — charging the budget is the
        conservative direction for admission (never under-shed)."""
        return float(req.nx) * float(req.ny) * float(max(req.steps, 1))

    def capacity_cells_per_s(self, req=None) -> float:
        """Modeled mesh capacity: chips x per-chip rate. The rate is,
        in order: the constructor's explicit rate, the tune db's
        measured rate for the request's shape on this device kind, the
        conservative default."""
        rate = self.per_chip_mcells_per_s
        if rate is None and req is not None:
            rate = tuned_rate_mcells(req.nx, req.ny,
                                     getattr(req, "dtype", "float32"))
        if rate is None:
            rate = DEFAULT_PER_CHIP_MCELLS_PER_S
        return rate * 1e6 * self.n_devices

    # -- admission ----------------------------------------------------- #

    def admit(self, req) -> Optional[Rejected]:
        """Charge ``req`` to the window, or return the structured
        rejection (``Rejected("mesh_saturated")``) WITHOUT charging —
        shed work must not consume the capacity it was refused.

        Non-solve request kinds (inverse optimizations) pass through
        unpriced: the scheduler routes them OFF the mesh (single-chip,
        their own dispatch lane), so they consume no mesh capacity —
        and ``work_cells`` would under-charge an iterations-long
        optimization loop by orders of magnitude anyway. Their own
        lane's deadline/breaker plumbing bounds them."""
        if getattr(req, "request_kind", "solve") != "solve":
            return None
        now = self.clock()
        work = self.work_cells(req)
        capacity = self.capacity_cells_per_s(req)
        limit = capacity * self.headroom * self.window_s
        with self._lock:
            cut = now - self.window_s
            self._window = [(t, w) for t, w in self._window if t > cut]
            pending = sum(w for _, w in self._window)
            ok = pending + work <= limit
            if ok:
                self._window.append((now, work))
            offered = (pending + work) / self.window_s
        self._emit(offered, capacity, shed=not ok)
        if ok:
            return None
        return Rejected(
            "mesh_saturated",
            f"modeled mesh saturation: offered {offered:.3g} cells/s "
            f"over a {self.window_s}s window exceeds {self.headroom}x "
            f"the modeled {capacity:.3g} cells/s mesh capacity "
            f"({self.n_devices} chips)",
            offered_cells_per_s=offered,
            capacity_cells_per_s=capacity)

    def _emit(self, offered: float, capacity: float, shed: bool) -> None:
        if self.registry is None:
            return
        self.registry.gauge("mesh_offered_cells_per_s", offered)
        self.registry.gauge("mesh_capacity_cells_per_s", capacity)
        if shed:
            self.registry.counter("mesh_admission_shed_total")
