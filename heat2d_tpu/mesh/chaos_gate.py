"""The mesh fault-tolerance gate — every device-level recovery path
MEASURED, not scheduled (CI ``mesh-chaos-gate``; docs/RESILIENCE.md).

Three scenarios, each injected by the chaos harness on the live mesh
and each required to recover AUTOMATICALLY to a bitwise-correct
answer (the single-chip engine is the oracle — the mesh-vs-single
parity contract makes it one):

- **device loss** — ``DEVICE_FAIL_AT`` kills a device mid-soak: the
  engine quarantines it, re-forms the batch mesh over the 7
  survivors, re-pads to the new device multiple, and relaunches the
  SAME batch (in-flight members ride their single-flight futures).
- **silent bit flip** — ``FLIP_BIT`` corrupts one exponent bit of the
  result buffer: the ABFT checksum tier flags the launch, convicts
  and quarantines the owner device, and recomputes from the
  digest-verified inputs.
- **hung collective** — ``HANG_COLLECTIVE`` wedges a warm launch: the
  stall watchdog fires WITHIN its deadline (asserted against the hang
  duration — detection must beat the hang, or it detected nothing),
  probes convict the culprit, and the batch requeues on the
  survivors. The abandoned launch's eventual result is discarded and
  counted, never served.

Every scenario runs through a real ``SolveServer`` (admission ->
cache -> single-flight -> micro-batch -> the guarded mesh engine), so
the recovery path exercised is the one production traffic takes. The
``kind="mesh_chaos"`` run record carries per-scenario measured
detection/recovery seconds, parity verdicts, quarantine sets, and the
``no_quarantined_serving`` invariant over every served launch.
"""

from __future__ import annotations

import argparse
import time
from typing import Optional

NX, NY, STEPS = 24, 28, 8


def _requests(n: int, base: float):
    from heat2d_tpu.serve.schema import SolveRequest

    return [SolveRequest(cx=base + 0.01 * i, cy=0.11, nx=NX, ny=NY,
                         steps=STEPS, method="jnp") for i in range(n)]


def _oracle_bytes(requests) -> list:
    """The single-chip engine's answers (bitwise oracle)."""
    import numpy as np

    from heat2d_tpu.serve.engine import EnsembleEngine

    eng = EnsembleEngine(max_batch=len(requests))
    return [np.asarray(u).tobytes()
            for u, _ in eng.solve_batch(requests)]


def _run_scenario(name: str, chaos_cfg, policy, batch_base: float,
                  hang_s: Optional[float] = None) -> dict:
    """One injected scenario through a live SolveServer. Returns the
    record row; never leaves a campaign installed."""
    import numpy as np

    from heat2d_tpu.mesh.engine import MeshEnsembleEngine
    from heat2d_tpu.obs.metrics import MetricsRegistry
    from heat2d_tpu.resil import chaos
    from heat2d_tpu.serve.server import SolveServer

    registry = MetricsRegistry()
    chaos.install(chaos_cfg, registry)
    try:
        engine = MeshEnsembleEngine(registry=registry, fault=policy)
        server = SolveServer(registry=registry, engine=engine,
                             max_batch=engine.max_batch,
                             default_timeout=120.0)
        with server:
            # Warm the signature (mesh launch attempt 1): compiles are
            # exempt from the stall deadline by design, and every
            # campaign here arms its fault at attempt 2 — a WARM
            # launch, the steady-state traffic faults actually hit.
            warm = _requests(engine.n_devices, 0.05)
            for f in [server.submit(r) for r in warm]:
                f.result(120)
            victims = _requests(engine.n_devices, batch_base)
            t0 = time.monotonic()
            futures = [server.submit(r) for r in victims]
            answers = [f.result(120) for f in futures]
            recovered_s = time.monotonic() - t0
        oracle = _oracle_bytes(victims)
        got = [np.asarray(res.u).tobytes() for res in answers]
        bitwise = got == oracle
        if hang_s is not None:
            # let the abandoned hung launch finish so its discard is
            # observable in the counters (bounded by the hang length)
            time.sleep(hang_s + 0.5)
        snap = engine.fault_snapshot()
        counters = {
            k: v for k, v in registry.snapshot()["counters"].items()
            if k.startswith(("mesh_", "resil_chaos"))}
        recoveries = snap["recoveries"]
        row = {
            "scenario": name,
            "bitwise": bitwise,
            "recovered": bool(recoveries),
            "recovery_s": (recoveries[0]["recovery_s"]
                           if recoveries else None),
            "e2e_recovered_s": recovered_s,
            "requeues": (recoveries[0]["requeues"]
                         if recoveries else 0),
            "quarantined": snap["health"]["quarantined"],
            "invariant": snap["invariant"],
            "counters": counters,
        }
        if hang_s is not None:
            # the watchdog must beat the hang: submit -> recovered in
            # less than the hang itself (detection at the deadline +
            # the relaunch), or the "detection" just waited the hang
            # out and detected nothing
            row["detected_within_deadline"] = recovered_s < hang_s
        return row
    finally:
        chaos.uninstall()


def run_gate() -> dict:
    """All three scenarios; returns the ``kind="mesh_chaos"`` record
    payload (caller wraps/writes)."""
    from heat2d_tpu.mesh.degrade import FaultPolicy
    from heat2d_tpu.resil.chaos import ChaosConfig

    # generous vs the stall deadline (0.4s): the recovery also pays a
    # cold compile on the survivor mesh, and detection must beat the
    # hang with margin on a loaded CI host
    hang_s = 3.0
    scenarios = [
        _run_scenario(
            "device_loss",
            ChaosConfig(device_fail_at=2, device_fail_index=3),
            FaultPolicy(stall_deadline_s=30.0), 0.16),
        _run_scenario(
            "bit_flip",
            ChaosConfig(flip_bit=2),
            FaultPolicy(abft=True), 0.2),
        _run_scenario(
            "hung_collective",
            ChaosConfig(hang_collective=2, hang_collective_s=hang_s,
                        device_fail_index=1),
            FaultPolicy(stall_deadline_s=0.4, max_requeues=3), 0.24,
            hang_s=hang_s),
    ]
    passed = all(
        s["bitwise"] and s["recovered"] and s["invariant"]["ok"]
        and s["recovery_s"] is not None and s["recovery_s"] > 0.0
        and s.get("detected_within_deadline", True)
        and s["quarantined"]
        for s in scenarios)
    return {"scenarios": scenarios, "passed": passed}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-mesh-chaos",
        description="mesh fault-tolerance gate: device loss, silent "
                    "bit flip, hung collective — measured recovery "
                    "with bitwise parity (docs/RESILIENCE.md)")
    p.add_argument("--out", default=None,
                   help="write the kind='mesh_chaos' run record here")
    args = p.parse_args(argv)

    import jax

    nd = len(jax.devices())
    if nd < 2:
        print(f"mesh-chaos-gate needs a multi-device mesh, have {nd} "
              f"(hint: XLA_FLAGS=--xla_force_host_platform_"
              f"device_count=8)")
        return 2

    payload = run_gate()
    from heat2d_tpu.obs.record import build_record

    rec = build_record("mesh_chaos", extra=payload)
    if args.out:
        from heat2d_tpu.io.binary import write_json_atomic

        write_json_atomic(rec, args.out, sort_keys=True)
    for s in payload["scenarios"]:
        print(f"  {s['scenario']:16s} bitwise={s['bitwise']} "
              f"recovery={s['recovery_s'] and round(s['recovery_s'], 3)}s "
              f"requeues={s['requeues']} "
              f"quarantined={s['quarantined']} "
              f"invariant={'ok' if s['invariant']['ok'] else 'VIOLATED'}")
    if payload["passed"]:
        print("mesh-chaos-gate passed: every device fault recovered "
              "automatically, measured, bitwise-correct")
        return 0
    print("mesh-chaos-gate FAILED")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
