"""Static spatial-footprint derivation — an offset-interval abstract
interpreter over jaxprs.

The problem registry (problems/base.py) is a *declared* contract:
``FamilySpec.halo_width`` drives the ghost-row depth the band kernels
gather (``w * T`` rows per sweep), the boundary-ring width every
keep-mask holds, and the shard-seam geometry of the fused halo route.
Nothing checked those declarations against what the traced kernels
actually *do* — a family whose kernel reads one row wider than its
declared halo silently corrupts shard seams. This module derives the
TRUE spatial access radius of a kernel from its jaxpr, so the registry
contract becomes machine-checked (analysis/ir.py wires it into the
``ir-gate``).

Abstract domain: per traced array, per axis, an **offset interval**
``[lo, hi]`` meaning "element ``j`` of this array depends on tracked-
input elements in ``[j+lo, j+hi]``". The tracked input (the state grid
``u``) starts at ``[0, 0]``; arrays with no data dependence on it are
``BOT`` (coefficient fields, iota masks, scalars); anything the domain
cannot express collapses to ``TOP`` carrying the primitive that caused
it (an *underivable* footprint is a finding, never a silent pass).

Transfer functions cover the primitives stencil kernels lower to —
``slice`` / ``pad`` / ``concatenate`` / ``scatter``-as-update /
``dynamic_(update_)slice`` / ``conv_general_dilated`` / ``transpose``
/ elementwise joins — plus descent into ``pjit``/call sub-jaxprs.
(``jnp.roll`` lowers to concatenate-of-slices, so rolls ride the
slice/concatenate rules.) Every interval bound carries the name of the
primitive that last widened it, so a footprint violation NAMES the
responsible primitive, not just the number.

As a side product the interpreter counts **coefficient-field reads**:
distinct interior-sized arrays with no dependence on ``u`` that feed
``u``-dependent arithmetic (varcoef's per-cell diffusivity fields).
``1 + coef_reads`` is the static witness for the declared
``FamilySpec.reads_per_step`` — the number the roofline ledger's
bytes/cell-step model streams (obs/roofline.py).

Pure host-side: everything here runs on ``jax.make_jaxpr`` output and
never executes a program.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: elementwise primitives: output dependence = join of operand
#: dependences (operands of lower rank are broadcast constants)
ELEMENTWISE = {
    "add", "sub", "mul", "div", "neg", "max", "min", "pow",
    "integer_pow", "exp", "log", "tanh", "sqrt", "rsqrt", "abs",
    "sign", "floor", "ceil", "round", "rem", "select_n", "and", "or",
    "xor", "not", "eq", "ne", "lt", "le", "gt", "ge", "square",
    "logistic", "erf", "sin", "cos", "tan", "atan2", "clamp",
    "is_finite", "nextafter", "copy", "stop_gradient", "real", "imag",
    "convert_element_type", "reduce_precision",
}

#: primitives that never carry a data dependence out of thin air
PURE_SOURCES = {"iota", "broadcast_in_dim"}


class _Top:
    """Underivable dependence; remembers the primitive that caused it."""

    __slots__ = ("why",)

    def __init__(self, why: str):
        self.why = why

    def __repr__(self):
        return f"TOP({self.why})"


@dataclasses.dataclass(frozen=True)
class Interval:
    """Per-axis offset intervals with witness primitives per bound."""

    lo: Tuple[int, ...]
    hi: Tuple[int, ...]
    wit_lo: Tuple[str, ...]
    wit_hi: Tuple[str, ...]

    @classmethod
    def zero(cls, rank: int, wit: str = "input") -> "Interval":
        return cls((0,) * rank, (0,) * rank, (wit,) * rank,
                   (wit,) * rank)

    def shift(self, axis: int, delta: int, wit: str) -> "Interval":
        delta = int(delta)      # padding configs carry np.int64
        lo, hi = list(self.lo), list(self.hi)
        wl, wh = list(self.wit_lo), list(self.wit_hi)
        lo[axis] += delta
        hi[axis] += delta
        if delta:
            wl[axis], wh[axis] = wit, wit
        return Interval(tuple(lo), tuple(hi), tuple(wl), tuple(wh))

    def widen(self, axis: int, lo: int, hi: int, wit: str) -> "Interval":
        lo, hi = int(lo), int(hi)
        nlo, nhi = list(self.lo), list(self.hi)
        wl, wh = list(self.wit_lo), list(self.wit_hi)
        if self.lo[axis] + lo < nlo[axis]:
            nlo[axis] += lo
            wl[axis] = wit
        else:
            nlo[axis] += lo
        if self.hi[axis] + hi > nhi[axis]:
            nhi[axis] += hi
            wh[axis] = wit
        else:
            nhi[axis] += hi
        return Interval(tuple(nlo), tuple(nhi), tuple(wl), tuple(wh))


def _join(a: Optional[Interval], b: Optional[Interval]):
    """Lattice join. ``None`` is BOT; ``_Top`` dominates."""
    if isinstance(a, _Top):
        return a
    if isinstance(b, _Top):
        return b
    if a is None:
        return b
    if b is None:
        return a
    if len(a.lo) != len(b.lo):
        return _Top("rank-mismatched join")
    lo, hi, wl, wh = [], [], [], []
    for i in range(len(a.lo)):
        if a.lo[i] <= b.lo[i]:
            lo.append(a.lo[i])
            wl.append(a.wit_lo[i])
        else:
            lo.append(b.lo[i])
            wl.append(b.wit_lo[i])
        if a.hi[i] >= b.hi[i]:
            hi.append(a.hi[i])
            wh.append(a.wit_hi[i])
        else:
            hi.append(b.hi[i])
            wh.append(b.wit_hi[i])
    return Interval(tuple(lo), tuple(hi), tuple(wl), tuple(wh))


@dataclasses.dataclass
class FootprintResult:
    """Derived dependence of a program's first output on its tracked
    input array."""

    #: per-axis (lo, hi) offsets, or None when underivable
    lo: Optional[Tuple[int, ...]]
    hi: Optional[Tuple[int, ...]]
    #: primitive that set each bound (names the culprit in findings)
    wit_lo: Tuple[str, ...]
    wit_hi: Tuple[str, ...]
    #: when not None: the primitive the domain could not express
    top: Optional[str]
    #: distinct interior-sized non-input-dependent arrays feeding
    #: input-dependent arithmetic (coefficient fields)
    coef_reads: int

    @property
    def derivable(self) -> bool:
        return self.top is None and self.lo is not None

    def radius(self, axis: int) -> int:
        """max(|lo|, hi): the stencil access radius along ``axis``."""
        assert self.lo is not None and self.hi is not None
        return max(-self.lo[axis], self.hi[axis], 0)

    def radii(self) -> Tuple[int, ...]:
        assert self.lo is not None
        return tuple(self.radius(a) for a in range(len(self.lo)))

    def witness(self, axis: int) -> str:
        """The primitive responsible for the widest offset on ``axis``."""
        assert self.lo is not None and self.hi is not None
        if -self.lo[axis] >= self.hi[axis]:
            return self.wit_lo[axis]
        return self.wit_hi[axis]


# ------------------------------------------------------------------ #
# constant folding for index operands (scatter/dus starts)
# ------------------------------------------------------------------ #

_CONST_MAX_SIZE = 16


def _literal_const(var) -> Optional[np.ndarray]:
    val = getattr(var, "val", None)
    if val is None:
        return None
    arr = np.asarray(val)
    if arr.size <= _CONST_MAX_SIZE:
        return arr
    return None


def _fold_const(eqn, const_env: Dict[int, np.ndarray],
                operands: List[Optional[np.ndarray]]):
    """Tiny integer constant folder: enough to resolve the index
    vectors ``.at[].set`` builds (broadcast of literal -> concatenate)."""
    name = eqn.primitive.name
    try:
        if name == "broadcast_in_dim" and operands[0] is not None:
            return np.broadcast_to(
                operands[0], eqn.params["shape"]).copy()
        if name == "concatenate" and all(
                o is not None for o in operands):
            return np.concatenate(operands,
                                  axis=eqn.params["dimension"])
        if name == "convert_element_type" and operands[0] is not None:
            return operands[0].astype(
                np.dtype(eqn.params["new_dtype"]))
        if name in ("reshape", "squeeze") and operands[0] is not None:
            shape = eqn.params.get("new_sizes")
            if shape is None:
                shape = eqn.outvars[0].aval.shape
            return operands[0].reshape(shape)
        if name in ("add", "sub", "mul") and all(
                o is not None for o in operands):
            op = {"add": np.add, "sub": np.subtract,
                  "mul": np.multiply}[name]
            return op(operands[0], operands[1])
    except Exception:
        return None
    return None


# ------------------------------------------------------------------ #
# the interpreter
# ------------------------------------------------------------------ #

def _axis_map(old_shape, new_shape) -> Optional[Dict[int, int]]:
    """Map old axis -> new axis for reshapes that only insert/remove
    unit axes (the ``expand_dims`` pattern conv kernels use); None for
    genuine reshapes."""
    old_nz = [(i, d) for i, d in enumerate(old_shape) if d != 1]
    new_nz = [(i, d) for i, d in enumerate(new_shape) if d != 1]
    if [d for _, d in old_nz] != [d for _, d in new_nz]:
        return None
    return {o: n for (o, _), (n, _) in zip(old_nz, new_nz)}


def _remap(val: Interval, amap: Dict[int, int], old_rank: int,
           new_rank: int, wit: str):
    """Carry intervals through a unit-axis reshape. Dropped axes must
    carry no offset (a unit axis cannot hold a stencil footprint)."""
    lo = [0] * new_rank
    hi = [0] * new_rank
    wl = [wit] * new_rank
    wh = [wit] * new_rank
    for o in range(old_rank):
        if o in amap:
            n = amap[o]
            lo[n], hi[n] = val.lo[o], val.hi[o]
            wl[n], wh[n] = val.wit_lo[o], val.wit_hi[o]
        elif val.lo[o] != 0 or val.hi[o] != 0:
            return _Top(wit)
    return Interval(tuple(lo), tuple(hi), tuple(wl), tuple(wh))


class _Interp:
    def __init__(self):
        self.env: Dict[int, object] = {}        # id(var) -> dep value
        self.const: Dict[int, np.ndarray] = {}  # id(var) -> folded const
        #: id(root var) of coefficient-field reads (dep-free interior-
        #: sized arrays feeding dep-carrying eqns), keyed by the var's
        #: *view root* so two slices of one field count once
        self.coef_roots: Dict[int, Tuple[int, ...]] = {}
        self.view_parent: Dict[int, int] = {}   # pure-view lineage
        self.min_interior: Optional[Tuple[int, ...]] = None

    # -- env plumbing ------------------------------------------------ #

    def read(self, var):
        if hasattr(var, "val"):        # Literal
            return None
        return self.env.get(id(var))

    def read_const(self, var) -> Optional[np.ndarray]:
        lit = _literal_const(var)
        if lit is not None:
            return lit
        return self.const.get(id(var))

    def write(self, var, val) -> None:
        self.env[id(var)] = val

    def root_of(self, var) -> int:
        vid = id(var)
        seen = set()
        while vid in self.view_parent and vid not in seen:
            seen.add(vid)
            vid = self.view_parent[vid]
        return vid

    def note_coef_read(self, eqn) -> None:
        """An eqn whose output depends on the tracked input: any
        dep-free interior-sized float operand is a coefficient-field
        read."""
        if self.min_interior is None:
            return
        for v in eqn.invars:
            if hasattr(v, "val"):
                continue
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                continue
            if len(aval.shape) != len(self.min_interior):
                continue
            if not np.issubdtype(np.dtype(aval.dtype), np.floating):
                continue
            if any(d < m for d, m in zip(aval.shape,
                                         self.min_interior)):
                continue
            if self.read(v) is None:    # BOT: no input dependence
                self.coef_roots[self.root_of(v)] = tuple(aval.shape)

    # -- eqn dispatch ------------------------------------------------ #

    def eval_jaxpr(self, jaxpr, in_vals: Sequence[object],
                   const_vals: Optional[Sequence[object]] = None):
        for var, val in zip(jaxpr.invars, in_vals):
            self.write(var, val)
        consts = const_vals if const_vals is not None else \
            [None] * len(jaxpr.constvars)
        for var, val in zip(jaxpr.constvars, consts):
            self.write(var, val)
        for eqn in jaxpr.eqns:
            self.eval_eqn(eqn)
        return [self.read(v) for v in jaxpr.outvars]

    def _sub_jaxprs(self, eqn):
        subs = []
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for s in vals:
                if hasattr(s, "jaxpr") and hasattr(s, "consts"):
                    subs.append(s.jaxpr)
                elif hasattr(s, "eqns"):
                    subs.append(s)
        return subs

    def eval_eqn(self, eqn) -> None:
        name = eqn.primitive.name
        in_vals = [self.read(v) for v in eqn.invars]
        in_consts = [self.read_const(v) for v in eqn.invars]

        folded = _fold_const(eqn, self.const, in_consts)
        if folded is not None and len(eqn.outvars) == 1:
            self.const[id(eqn.outvars[0])] = folded

        out = self.transfer(eqn, name, in_vals, in_consts)
        if any(isinstance(v, Interval) for v in in_vals) or \
                isinstance(out, Interval):
            if isinstance(out, (Interval, _Top)) or out is None:
                if any(isinstance(v, Interval) for v in in_vals):
                    self.note_coef_read(eqn)
        if isinstance(out, list):
            for var, val in zip(eqn.outvars, out):
                self.write(var, val)
        else:
            for var in eqn.outvars:
                self.write(var, out)

    # -- transfer functions ------------------------------------------ #

    def transfer(self, eqn, name, in_vals, in_consts):
        deps = [v for v in in_vals if v is not None]
        if not deps:
            return None                 # closed under no-dependence
        if any(isinstance(v, _Top) for v in deps):
            return next(v for v in deps if isinstance(v, _Top))

        out_rank = None
        if eqn.outvars and hasattr(eqn.outvars[0], "aval") and \
                hasattr(eqn.outvars[0].aval, "shape"):
            out_rank = len(eqn.outvars[0].aval.shape)

        if name in ELEMENTWISE:
            out = None
            for v, var in zip(in_vals, eqn.invars):
                if v is None:
                    continue
                rank = len(var.aval.shape)
                if out_rank is not None and rank != out_rank:
                    return _Top(name)   # dep value broadcast up
                # implicit dim-1 broadcast of a dep value loses the
                # per-element correspondence on that axis
                if any(d1 == 1 and d2 != 1 for d1, d2 in zip(
                        var.aval.shape, eqn.outvars[0].aval.shape)):
                    return _Top(name)
                out = _join(out, v)
            return out

        if name == "slice":
            v = in_vals[0]
            strides = eqn.params.get("strides")
            if strides is not None and any(s != 1 for s in strides):
                return _Top("slice[strided]")
            for axis, start in enumerate(eqn.params["start_indices"]):
                v = v.shift(axis, start, "slice")
            return v

        if name == "pad":
            v, pad_val = in_vals[0], in_vals[1]
            if pad_val is not None:
                return _Top("pad")
            for axis, (lo, _hi, interior) in enumerate(
                    eqn.params["padding_config"]):
                if interior:
                    return _Top("pad[interior]")
                v = v.shift(axis, -lo, "pad")
            return v

        if name == "concatenate":
            dim = eqn.params["dimension"]
            out = None
            offset = 0
            for v, var in zip(in_vals, eqn.invars):
                size = var.aval.shape[dim]
                if v is not None and not isinstance(v, _Top):
                    out = _join(out, v.shift(dim, -offset,
                                             "concatenate"))
                elif isinstance(v, _Top):
                    return v
                offset += size
            return out

        if name in ("transpose",):
            perm = eqn.params["permutation"]
            v = in_vals[0]
            lo = tuple(v.lo[p] for p in perm)
            hi = tuple(v.hi[p] for p in perm)
            wl = tuple(v.wit_lo[p] for p in perm)
            wh = tuple(v.wit_hi[p] for p in perm)
            return Interval(lo, hi, wl, wh)

        if name in ("reshape", "squeeze", "expand_dims"):
            v = in_vals[0]
            old = eqn.invars[0].aval.shape
            new = eqn.outvars[0].aval.shape
            amap = _axis_map(old, new)
            if amap is None:
                return _Top(name)
            return _remap(v, amap, len(old), len(new), name)

        if name == "broadcast_in_dim":
            # unit-axis insertion of a dep value (the x[None, None]
            # idiom); genuine fan-out of a dep value loses per-element
            # correspondence -> TOP
            v = in_vals[0]
            bdims = eqn.params["broadcast_dimensions"]
            old_shape = eqn.invars[0].aval.shape
            new_shape = tuple(eqn.params["shape"])
            if any(old_shape[o] != new_shape[n]
                   for o, n in enumerate(bdims)):
                return _Top("broadcast_in_dim")
            rank = len(new_shape)
            lo = [0] * rank
            hi = [0] * rank
            wl = ["broadcast_in_dim"] * rank
            wh = ["broadcast_in_dim"] * rank
            for o, n in enumerate(bdims):
                lo[n], hi[n] = v.lo[o], v.hi[o]
                wl[n], wh[n] = v.wit_lo[o], v.wit_hi[o]
            return Interval(tuple(lo), tuple(hi), tuple(wl),
                            tuple(wh))

        if name == "dynamic_slice":
            v = in_vals[0]
            starts = [self.read_const(s) for s in eqn.invars[1:]]
            if any(s is None for s in starts) or any(
                    iv is not None for iv in in_vals[1:]):
                return _Top("dynamic_slice")
            for axis, s in enumerate(starts):
                v = v.shift(axis, int(s), "dynamic_slice")
            return v

        if name == "dynamic_update_slice":
            operand, update = in_vals[0], in_vals[1]
            starts = [self.read_const(s) for s in eqn.invars[2:]]
            if any(s is None for s in starts):
                return _Top("dynamic_update_slice")
            out = operand
            if update is not None:
                u = update
                for axis, s in enumerate(starts):
                    u = u.shift(axis, -int(s), "dynamic_update_slice")
                out = _join(out, u)
            return out

        if name == "scatter":
            return self._scatter(eqn, in_vals)

        if name == "conv_general_dilated":
            return self._conv(eqn, in_vals)

        if name in ("pjit", "closed_call", "core_call", "remat",
                    "remat2", "checkpoint", "custom_jvp_call",
                    "custom_vjp_call", "custom_jvp_call_jaxpr",
                    "custom_vjp_call_jaxpr", "named_call"):
            subs = self._sub_jaxprs(eqn)
            if len(subs) >= 1:
                sub = subs[0]
                if len(sub.invars) == len(eqn.invars):
                    outs = _Interp._spawn(self).eval_jaxpr(sub, in_vals)
                    if len(outs) == len(eqn.outvars):
                        return list(outs)
            return _Top(name)

        return _Top(name)

    @staticmethod
    def _spawn(parent: "_Interp") -> "_Interp":
        child = _Interp()
        child.min_interior = parent.min_interior
        child.coef_roots = parent.coef_roots      # shared accounting
        child.view_parent = parent.view_parent
        return child

    def _scatter(self, eqn, in_vals):
        """The ``.at[a:b, c:d].set`` lowering: a full-window scatter at
        constant start indices == dynamic_update_slice."""
        dnums = eqn.params.get("dimension_numbers")
        operand, _idx, update = in_vals[0], in_vals[1], in_vals[2]
        rank = len(eqn.invars[0].aval.shape)
        starts = self.read_const(eqn.invars[1])
        if dnums is None or starts is None:
            return _Top("scatter")
        if (tuple(dnums.update_window_dims) != tuple(range(rank))
                or dnums.inserted_window_dims
                or tuple(dnums.scatter_dims_to_operand_dims)
                != tuple(range(rank))):
            return _Top("scatter")
        starts = np.ravel(starts)
        if starts.size != rank:
            return _Top("scatter")
        if in_vals[1] is not None:
            return _Top("scatter[traced indices]")
        out = operand
        if update is not None:
            u = update
            for axis in range(rank):
                u = u.shift(axis, -int(starts[axis]), "scatter")
            out = _join(out, u)
        return out

    def _conv(self, eqn, in_vals):
        """Stride-1 spatial convolution: out[j] depends on
        in[j - pad_lo .. j - pad_lo + (k-1)*dil]."""
        lhs, rhs = in_vals[0], in_vals[1]
        if rhs is not None:
            return _Top("conv_general_dilated[traced rhs]")
        if lhs is None:
            return None
        p = eqn.params
        dn = p["dimension_numbers"]
        strides = p["window_strides"]
        if any(s != 1 for s in strides):
            return _Top("conv_general_dilated[strided]")
        if any(d != 1 for d in p.get("lhs_dilation") or []):
            return _Top("conv_general_dilated[lhs-dilated]")
        rhs_dil = p.get("rhs_dilation") or [1] * len(strides)
        k_shape = eqn.invars[1].aval.shape
        v = lhs
        for i, (lhs_ax, rhs_ax, out_ax) in enumerate(zip(
                dn.lhs_spec[2:], dn.rhs_spec[2:], dn.out_spec[2:])):
            if lhs_ax != out_ax:
                return _Top("conv_general_dilated[axis-permuted]")
            pad_lo, _pad_hi = p["padding"][i]
            reach = (k_shape[rhs_ax] - 1) * rhs_dil[i]
            v = v.widen(lhs_ax, -pad_lo, reach - pad_lo,
                        "conv_general_dilated")
        # batch/feature axes of the output must carry no offset
        return v


def derive_footprint(fn, *example_args, track: int = 0,
                     interior_margin: int = 8) -> FootprintResult:
    """Trace ``fn(*example_args)`` and derive the dependence of its
    first output on positional argument ``track`` (the state grid).

    ``interior_margin``: arrays are counted as coefficient-field reads
    only when every dim is within ``interior_margin`` of the tracked
    input's dims (grid-sized or interior-sized, not reduced summaries).
    """
    import jax

    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = closed.jaxpr
    tracked = jaxpr.invars[track]
    rank = len(tracked.aval.shape)
    interp = _Interp()
    interp.min_interior = tuple(
        max(1, d - interior_margin) for d in tracked.aval.shape)
    # record view lineage for coefficient-read dedup (pure views only)
    for eqn in _walk_eqns(jaxpr):
        if eqn.primitive.name in ("slice", "convert_element_type",
                                  "broadcast_in_dim", "reshape",
                                  "squeeze", "transpose") \
                and len(eqn.outvars) == 1 and eqn.invars \
                and not hasattr(eqn.invars[0], "val"):
            interp.view_parent[id(eqn.outvars[0])] = id(eqn.invars[0])

    in_vals: List[object] = [None] * len(jaxpr.invars)
    in_vals[track] = Interval.zero(rank)
    outs = interp.eval_jaxpr(jaxpr, in_vals)
    out = outs[0] if outs else None
    coef = len(interp.coef_roots)
    if isinstance(out, _Top):
        return FootprintResult(None, None, (), (), out.why, coef)
    if out is None:
        return FootprintResult((0,) * rank, (0,) * rank,
                               ("none",) * rank, ("none",) * rank,
                               None, coef)
    return FootprintResult(out.lo, out.hi, out.wit_lo, out.wit_hi,
                           None, coef)


def _walk_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for s in vals:
                if hasattr(s, "jaxpr") and hasattr(s, "consts"):
                    yield from _walk_eqns(s.jaxpr)
                elif hasattr(s, "eqns"):
                    yield from _walk_eqns(s)
