"""Dtype-flow census — per-program precision cards over jaxpr IR.

ROADMAP item 2 (bf16 storage / f32 compute) will make implicit dtype
casts the platform's dominant correctness hazard: one stray
``convert_element_type`` inside a traced kernel silently halves (or
doubles) the precision of every cell-step. This pass makes every cast
in a traced program *visible and accountable*:

- ``census_casts`` walks a ClosedJaxpr (descending into pjit / scan /
  while / cond / shard_map / pallas_call sub-jaxprs) and records every
  ``convert_element_type`` / ``reduce_precision`` equation with its
  provenance path — the chain of enclosing sub-jaxpr primitives, with
  jitted-function names (``pjit[_linspace]``) so a finding points at
  the Python source that introduced the cast.
- ``PrecisionCard`` is the per-program report: the full cast list plus
  ``findings(allowlist)`` — precision-relevant casts (a floating dtype
  on either side, dtype actually changed) not covered by the program's
  declared allowlist. Pure integer/bool index casts are listed on the
  card but are never findings: they cannot lose field precision.

The allowlist lives in the registry (``FamilySpec.cast_allowlist``),
mirroring the lint baseline's justified-entries workflow: a cast is
either declared where the family is declared, or it is a finding. An
allowlist entry that matches nothing is NOT an error — casts can be
flag-dependent (x64 tracing inserts float64→float32 narrowings that
non-x64 tracing never creates), and an entry must stay valid under
both.

Host-side only: operates on ``jax.make_jaxpr`` output, never runs a
program (analysis/ir.py proves the sweep leaves traced programs
byte-identical).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

#: cast-like primitives the census records
CAST_PRIMS = ("convert_element_type", "reduce_precision")


@dataclasses.dataclass(frozen=True)
class CastSite:
    """One (src → dst, provenance) cast class in a traced program.
    ``count`` aggregates identical sites (a vmapped/scanned cast traces
    once per call site, not per lane)."""

    src: str
    dst: str
    #: enclosing sub-jaxpr chain, outermost first, e.g.
    #: ("pjit[_linspace]",); () for a top-level cast
    path: Tuple[str, ...]
    count: int = 1

    @property
    def precision_relevant(self) -> bool:
        """Involves a floating dtype and actually changes dtype —
        the class of casts that can create/destroy field precision."""
        if self.src == self.dst:
            return False
        return (np.issubdtype(np.dtype(self.src), np.inexact)
                or np.issubdtype(np.dtype(self.dst), np.inexact))

    @property
    def narrowing(self) -> bool:
        """Loses mantissa/width (the dangerous direction)."""
        try:
            return (np.dtype(self.src).itemsize
                    > np.dtype(self.dst).itemsize)
        except TypeError:
            return False

    def describe(self) -> str:
        where = "/".join(self.path) if self.path else "<top>"
        arrow = "⤓" if self.narrowing else "→"
        n = f" ×{self.count}" if self.count > 1 else ""
        return f"{self.src} {arrow} {self.dst} at {where}{n}"


def _eqn_label(eqn) -> str:
    """Provenance label for a sub-jaxpr-carrying eqn: primitive name,
    plus the jitted/scanned function name when the params carry one."""
    name = eqn.primitive.name
    fn = eqn.params.get("name")
    if fn:
        return f"{name}[{fn}]"
    return name


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for s in vals:
            if hasattr(s, "jaxpr") and hasattr(
                    getattr(s, "jaxpr"), "eqns"):
                yield s.jaxpr            # ClosedJaxpr
            elif hasattr(s, "eqns"):
                yield s                  # raw Jaxpr


def census_casts(closed) -> List[CastSite]:
    """Every cast eqn in ``closed`` (a ClosedJaxpr or Jaxpr),
    recursively, aggregated by (src, dst, provenance path)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    agg: Dict[Tuple[str, str, Tuple[str, ...]], int] = {}

    def walk(jx, path: Tuple[str, ...]) -> None:
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in CAST_PRIMS and eqn.invars:
                aval = getattr(eqn.invars[0], "aval", None)
                src = str(np.dtype(aval.dtype)) if aval is not None \
                    else "?"
                if name == "reduce_precision":
                    dst = (f"reduced[e{eqn.params.get('exponent_bits')}"
                           f"m{eqn.params.get('mantissa_bits')}]")
                else:
                    dst = str(np.dtype(eqn.params["new_dtype"]))
                if src != dst:
                    key = (src, dst, path)
                    agg[key] = agg.get(key, 0) + 1
            for sub in _sub_jaxprs(eqn):
                walk(sub, path + (_eqn_label(eqn),))

    walk(jaxpr, ())
    return [CastSite(src=s, dst=d, path=p, count=c)
            for (s, d, p), c in sorted(agg.items(),
                                       key=lambda kv: kv[0])]


@dataclasses.dataclass
class PrecisionCard:
    """Per-program cast report: everything on the card, findings only
    for precision-relevant casts outside the declared allowlist."""

    program: str
    casts: List[CastSite]

    def findings(self, allowlist: Iterable[Tuple[str, str]] = ()
                 ) -> List[CastSite]:
        allowed = {tuple(a) for a in allowlist}
        return [c for c in self.casts
                if c.precision_relevant
                and (c.src, c.dst) not in allowed]

    def lines(self) -> List[str]:
        if not self.casts:
            return [f"{self.program}: no casts"]
        out = [f"{self.program}: {len(self.casts)} cast site(s)"]
        out.extend(f"  {c.describe()}" for c in self.casts)
        return out


def precision_card(program: str, fn, *args, **kwargs) -> PrecisionCard:
    """Trace ``fn(*args)`` and build its precision card."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return PrecisionCard(program=program, casts=census_casts(closed))


# ------------------------------------------------------------------ #
# collective census — shares the recursive walker (analysis/ir.py's
# collective-contract pass consumes this)
# ------------------------------------------------------------------ #

#: cross-device communication primitives worth a contract
COLLECTIVE_PRIMS = ("ppermute", "psum", "pmin", "pmax", "all_gather",
                    "all_to_all", "reduce_scatter", "pgather",
                    "psum_scatter", "pbroadcast")

#: trace-time aliases: jax versions split some collectives into
#: rewrite-pass twins (psum traces as ``psum2`` under modern
#: shard_map); the census reports the canonical name so contracts
#: stay version-independent
_CANONICAL = {"psum2": "psum", "all_gather_invariant": "all_gather"}


@dataclasses.dataclass(frozen=True)
class CollectiveSite:
    """One collective primitive occurrence class in a traced program."""

    prim: str
    path: Tuple[str, ...]
    count: int = 1
    #: for ppermute: the flattened (src, dst) pairs (dedup'd)
    perms: Tuple[Tuple[int, int], ...] = ()

    def describe(self) -> str:
        where = "/".join(self.path) if self.path else "<top>"
        n = f" ×{self.count}" if self.count > 1 else ""
        return f"{self.prim} at {where}{n}"


def census_collectives(closed) -> List[CollectiveSite]:
    """Every collective eqn in ``closed``, recursively, aggregated by
    (primitive, provenance path); ppermute sites carry their
    permutation pairs so contract checks can assert nearest-neighbor
    structure."""
    jaxpr = getattr(closed, "jaxpr", closed)
    agg: Dict[Tuple[str, Tuple[str, ...]], List] = {}

    def walk(jx, path: Tuple[str, ...]) -> None:
        for eqn in jx.eqns:
            name = _CANONICAL.get(eqn.primitive.name,
                                  eqn.primitive.name)
            if name in COLLECTIVE_PRIMS:
                key = (name, path)
                entry = agg.setdefault(key, [0, set()])
                entry[0] += 1
                perm = eqn.params.get("perm")
                if perm:
                    entry[1].update((int(a), int(b)) for a, b in perm)
            for sub in _sub_jaxprs(eqn):
                walk(sub, path + (_eqn_label(eqn),))

    walk(jaxpr, ())
    return [CollectiveSite(prim=p, path=pa, count=c,
                           perms=tuple(sorted(perms)))
            for (p, pa), (c, perms) in sorted(agg.items(),
                                              key=lambda kv: kv[0])]
