"""Repo-specific AST linter — the conventions the test suite relies on,
as machine-checked rules.

Six rules, each encoding an invariant this codebase already enforces by
hand (docs/ANALYSIS.md has the rationale + an example finding for each):

- **R001 atomic-write discipline** — ``open(path, "w"/"wb")`` on a
  persistent artifact must flow through the tmp + fsync + ``os.replace``
  idiom (io/binary.py's commit protocol). A direct write can be torn by
  a crash and then *load* as a valid artifact. Exempt: staging paths
  (the expression mentions ``tmp``) and functions that themselves
  ``os.replace`` (they ARE the idiom).
- **R002 no wall-clock/RNG in traced code** — ``time.*``,
  ``datetime.now``, ``random.*`` inside a traced scope bake one
  trace-time value into the compiled program (and differ across ranks:
  the multihost lockstep hazard).
- **R003 traced-value leaks** — ``float()``/``int()``/``bool()`` /
  ``.item()`` on array values inside traced scopes force a
  ConcretizationError at best, a silent host sync at worst.
- **R004 chaos purity** — ``resil/chaos.py`` may not import or touch
  jax: the chaos jaxpr pin (armed == disarmed program) is only
  structural if the module *cannot* reach a traced value.
- **R005 metric/doc drift** — every metric family instantiated through
  the obs registry must appear in the docs tables, and every documented
  family must exist in code (dashboards built from the docs must not
  silently watch nothing).
- **R006 bare locks in serve/fleet/resil/mesh** — threaded subsystems
  must take their mutexes from ``analysis.locks`` so the lock audit
  (``HEAT2D_LOCK_AUDIT=1``) sees every acquisition.

Pure stdlib ``ast`` — no third-party parser; runs in CI as the
``lint-gate`` job via the ``heat2d-tpu-lint`` CLI (analysis/cli.py),
which holds the tree at zero non-baselined findings.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

ALL_RULES = ("R001", "R002", "R003", "R004", "R005", "R006")

#: directory names never scanned
SKIP_DIRS = {"tests", "__pycache__", ".git", ".claude", "benchmarks"}

#: skipped only when they are NOT python packages: "dist"/"build" name
#: setuptools output at the repo root, but heat2d_tpu/dist/ (the pod
#: runtime) is source — the __init__.py is the tiebreaker
ARTIFACT_DIRS = {"build", "dist"}

#: callees whose function-valued arguments become traced scopes
TRACER_CALLS = {
    "jit", "pallas_call", "shard_map", "shard_map_compat", "vmap",
    "pmap", "grad", "value_and_grad", "fori_loop", "while_loop",
    "scan", "cond", "switch", "remat", "checkpoint", "custom_vjp",
    "custom_jvp", "defvjp", "make_jaxpr", "named_call",
}

#: callees whose function-valued arguments run on the HOST (never mark
#: their arguments traced even when lexically inside a tracer call)
HOST_CALLS = {
    "callback", "debug_callback", "pure_callback", "io_callback",
    "Thread", "submit", "partial",
}

#: registry-binding callees whose function-valued keyword arguments
#: become traced scopes ACROSS modules: the problems registry binds
#: kernels as ``Family(step=_k.heat5_step, ...)`` in a different
#: module from the kernel definitions, so the per-module traced-scope
#: fixpoint alone never sees them — a wall-clock/RNG leak inside a new
#: family's kernel would lint clean. ``lint_tree`` collects the bound
#: names in a cross-file pre-pass and seeds them as R002 roots.
REGISTRY_BINDERS = {"Family"}

#: Family(...) keyword fields whose values run under trace (np_step is
#: the host-side numpy oracle, mode_factor is host-side scheduling
#: math — neither is traced)
REGISTRY_TRACED_FIELDS = {"step", "step_value", "scalars"}

#: wall-clock / RNG call chains banned inside traced scopes (R002)
WALLCLOCK_ROOTS = {"time", "random"}
WALLCLOCK_DATETIME_ATTRS = {"now", "utcnow", "today"}

METRIC_METHODS = {"counter", "gauge", "observe", "series", "timer"}

#: metric families the drift rule covers (names outside these prefixes
#: are not part of the documented contract)
METRIC_RE = re.compile(
    r"^(serve|fleet|resil|tune|inverse|slo|load|control|mesh|adi|mg"
    r"|perf|problem|ir|analysis|autoscale|dist)_[a-z0-9_]+$")

#: keyword names whose literal string values name a metric family
#: (e.g. ``SingleFlight(counter="fleet_coalesced_total")``)
METRIC_KEYWORDS = {"counter", "metric", "name"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str           # posix-relative to the scanned root
    line: int
    context: str        # enclosing qualname, or a rule-specific tag
    match: str          # short source snippet (baseline identity)
    message: str

    @property
    def key(self) -> str:
        """Line-number-free identity: a finding keeps its baseline
        entry across unrelated edits to the same file."""
        return f"{self.rule}:{self.path}:{self.context}:{self.match}"

    def render(self) -> str:
        return (f"{self.rule} {self.path}:{self.line} [{self.context}] "
                f"{self.message}  ->  {self.match}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self) | {"key": self.key}


class BaselineError(ValueError):
    """A malformed baseline file (entry without a justification, bad
    schema) — a grandfathered finding without a WHY is just a
    suppressed finding."""


def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """{finding key: justification}. Every entry must carry a
    non-empty ``justification`` string."""
    if path is None or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    entries = data.get("findings")
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: expected a 'findings' list")
    out: Dict[str, str] = {}
    for e in entries:
        key = e.get("key")
        just = e.get("justification")
        if not key or not isinstance(key, str):
            raise BaselineError(f"{path}: entry missing 'key': {e}")
        if not just or not isinstance(just, str) or not just.strip():
            raise BaselineError(
                f"{path}: baselined finding {key!r} has no "
                "justification — grandfathering requires a reason")
        out[key] = just
    return out


# ------------------------------------------------------------------ #
# shared AST plumbing
# ------------------------------------------------------------------ #

def _attr_chain(node: ast.AST) -> List[str]:
    """``a.b.c(...)`` -> ["a", "b", "c"]; empty when not a plain
    name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _terminal_name(func: ast.AST) -> Optional[str]:
    chain = _attr_chain(func)
    return chain[-1] if chain else None


def _snippet(src_lines: List[str], node: ast.AST, limit: int = 96) -> str:
    try:
        text = ast.get_source_segment("\n".join(src_lines), node)
    except Exception:
        text = None
    if not text:
        line = src_lines[node.lineno - 1] if node.lineno - 1 < len(
            src_lines) else ""
        text = line.strip()
    text = " ".join(text.split())
    return text[:limit]


class _Scopes(ast.NodeVisitor):
    """Function table + parent/qualname maps for one module."""

    def __init__(self, tree: ast.Module):
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.functions: List[ast.AST] = []
        self.qualnames: Dict[ast.AST, str] = {}
        self.module_funcs: Dict[str, ast.AST] = {}
        self._stack: List[str] = []
        self._class_depth = 0
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._visit_block(tree)

    def _visit_block(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = ".".join(self._stack + [child.name])
                self.qualnames[child] = qn
                self.functions.append(child)
                if not self._stack:
                    self.module_funcs[child.name] = child
                self._stack.append(child.name)
                self._visit_block(child)
                self._stack.pop()
            elif isinstance(child, ast.Lambda):
                qn = ".".join(self._stack + ["<lambda>"])
                self.qualnames[child] = qn
                self.functions.append(child)
                self._visit_block(child)
            elif isinstance(child, ast.ClassDef):
                self._stack.append(child.name)
                self._visit_block(child)
                self._stack.pop()
            else:
                self._visit_block(child)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def context_of(self, node: ast.AST) -> str:
        fn = self.enclosing_function(node)
        return self.qualnames.get(fn, "<module>") if fn is not None \
            else "<module>"


def _function_nodes_within(fn: ast.AST) -> Iterable[ast.AST]:
    yield fn
    for sub in ast.walk(fn):
        if sub is not fn and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield sub


def registry_bound_names(trees: Dict[str, ast.Module]) -> Set[str]:
    """Cross-file pre-pass: function names bound into the problems
    registry's traced slots (``Family(step=..., step_value=...,
    scalars=...)``) anywhere in the tree. These seed the per-module
    traced-scope fixpoint, so kernels reached only through registry
    dispatch are visible to R002/R003."""
    bound: Set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in REGISTRY_BINDERS:
                continue
            for kw in node.keywords:
                if kw.arg not in REGISTRY_TRACED_FIELDS:
                    continue
                if isinstance(kw.value, (ast.Name, ast.Attribute)):
                    name = _terminal_name(kw.value)
                    if name:
                        bound.add(name)
    return bound


def _traced_functions(tree: ast.Module, scopes: _Scopes,
                      extra_roots: Set[str] = frozenset()
                      ) -> Set[ast.AST]:
    """The traced-scope set: functions handed to jit/pallas_call/
    shard_map/lax control flow (directly, by name, or through
    ``functools.partial``), ``*_kernel`` functions (the Pallas kernel
    convention), functions decorated with a tracer, names bound into
    the problems registry's traced slots (``extra_roots`` — the
    cross-file ``registry_bound_names`` pre-pass), everything
    lexically nested in those — then closed over same-module calls
    (a traced body calling a module-level helper traces the helper)."""
    roots: Set[ast.AST] = set()

    for fn in scopes.functions:
        name = getattr(fn, "name", "")
        if name.endswith("_kernel") or name in extra_roots:
            roots.add(fn)
        for deco in getattr(fn, "decorator_list", []):
            for sub in ast.walk(deco):
                t = _terminal_name(sub) if isinstance(
                    sub, (ast.Name, ast.Attribute)) else None
                if t in TRACER_CALLS:
                    roots.add(fn)

    def mark_arg(arg: ast.AST) -> None:
        if isinstance(arg, ast.Lambda):
            roots.add(arg)
        elif isinstance(arg, ast.Name):
            target = scopes.module_funcs.get(arg.id)
            if target is not None:
                roots.add(target)
            else:
                # a locally-defined function passed by name
                for fn in scopes.functions:
                    if getattr(fn, "name", None) == arg.id:
                        roots.add(fn)
        elif isinstance(arg, ast.Call):
            t = _terminal_name(arg.func)
            if t in HOST_CALLS and t != "partial":
                return
            for a in list(arg.args) + [k.value for k in arg.keywords]:
                mark_arg(a)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        t = _terminal_name(node.func)
        if t not in TRACER_CALLS:
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            mark_arg(arg)

    traced: Set[ast.AST] = set()
    for r in roots:
        traced.update(_function_nodes_within(r))

    # fixpoint: same-module calls out of traced bodies
    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name):
                    callee = scopes.module_funcs.get(node.func.id)
                    if callee is not None and callee not in traced:
                        for sub in _function_nodes_within(callee):
                            if sub not in traced:
                                traced.add(sub)
                                changed = True
    return traced


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in list(a.posonlyargs) + list(a.args)
             + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


# ------------------------------------------------------------------ #
# per-file rules
# ------------------------------------------------------------------ #

def _iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in SKIP_DIRS
            and (d not in ARTIFACT_DIRS or os.path.isfile(
                os.path.join(dirpath, d, "__init__.py"))))
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _relpath(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def _write_mode(node: ast.Call) -> Optional[str]:
    """The constant mode string of an ``open`` call when it opens for
    (over)writing — "w"/"wb"/"w+"...; None otherwise."""
    mode: Optional[ast.AST] = None
    if len(node.args) >= 2:
        mode = node.args[1]
    else:
        for k in node.keywords:
            if k.arg == "mode":
                mode = k.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and "w" in mode.value:
        return mode.value
    return None


def _rule_r001(rel: str, tree: ast.Module, scopes: _Scopes,
               src_lines: List[str]) -> List[Finding]:
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "open" and node.args):
            continue
        mode = _write_mode(node)
        if mode is None:
            continue
        path_src = _snippet(src_lines, node.args[0])
        if "tmp" in path_src.lower():
            continue                    # a staging file: the idiom's
            #                             first half, committed later
        fn = scopes.enclosing_function(node)
        search_in = fn if fn is not None else tree
        has_replace = any(
            isinstance(n, ast.Call)
            and _attr_chain(n.func)[-2:] == ["os", "replace"]
            for n in ast.walk(search_in))
        if has_replace:
            continue                    # the tmp+replace idiom inline
        out.append(Finding(
            "R001", rel, node.lineno, scopes.context_of(node),
            _snippet(src_lines, node),
            f"direct open(..., {mode!r}) on a persistent artifact — "
            "use the tmp + fsync + os.replace idiom "
            "(io.binary.write_text_atomic / write_json_atomic)"))
    return out


def _rule_r002_r003(rel: str, tree: ast.Module, scopes: _Scopes,
                    src_lines: List[str], rules: Set[str],
                    extra_roots: Set[str] = frozenset()
                    ) -> List[Finding]:
    out: List[Finding] = []
    traced = _traced_functions(tree, scopes, extra_roots)
    if not traced:
        return out
    traced_params: Dict[ast.AST, Set[str]] = {}

    def params_in_scope(fn: ast.AST) -> Set[str]:
        if fn not in traced_params:
            names: Set[str] = set()
            cur: Optional[ast.AST] = fn
            while cur is not None and cur in traced:
                names |= _param_names(cur)
                cur = scopes.enclosing_function(cur)
            traced_params[fn] = names
        return traced_params[fn]

    for fn in traced:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            owner = scopes.enclosing_function(node)
            if owner not in traced:
                continue                # nested host fn inside traced?
            chain = _attr_chain(node.func)
            if "R002" in rules and chain:
                rooted = chain[0]
                term = chain[-1]
                bad = (
                    (rooted in WALLCLOCK_ROOTS and len(chain) > 1)
                    or ("datetime" in chain
                        and term in WALLCLOCK_DATETIME_ATTRS)
                    or (len(chain) >= 2 and chain[-2] == "random"
                        and rooted in ("np", "numpy"))
                )
                if bad:
                    out.append(Finding(
                        "R002", rel, node.lineno,
                        scopes.context_of(node),
                        _snippet(src_lines, node),
                        "wall-clock/RNG call inside a traced scope — "
                        "the value is baked in at trace time (use a "
                        "host-side hook, or jax.random with an "
                        "explicit key)"))
            if "R003" in rules:
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item":
                    out.append(Finding(
                        "R003", rel, node.lineno,
                        scopes.context_of(node),
                        _snippet(src_lines, node),
                        ".item() on a value inside a traced scope — "
                        "concretizes the tracer (host sync / error)"))
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")
                        and node.args):
                    mentioned = {n.id for n in ast.walk(node.args[0])
                                 if isinstance(n, ast.Name)}
                    if mentioned & params_in_scope(owner):
                        out.append(Finding(
                            "R003", rel, node.lineno,
                            scopes.context_of(node),
                            _snippet(src_lines, node),
                            f"{node.func.id}() applied to a traced "
                            "value inside a traced scope — leaks the "
                            "tracer to the host"))
    return out


def _rule_r004(rel: str, tree: ast.Module, scopes: _Scopes,
               src_lines: List[str]) -> List[Finding]:
    if not rel.endswith("resil/chaos.py"):
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax" or alias.name.startswith("jax."):
                    out.append(Finding(
                        "R004", rel, node.lineno,
                        scopes.context_of(node),
                        _snippet(src_lines, node),
                        "chaos hooks must stay jax-free: the armed == "
                        "disarmed jaxpr pin is only structural if this "
                        "module cannot reach a traced value"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax" or mod.startswith("jax."):
                out.append(Finding(
                    "R004", rel, node.lineno, scopes.context_of(node),
                    _snippet(src_lines, node),
                    "chaos hooks must stay jax-free (import from jax)"))
        elif isinstance(node, ast.Attribute):
            chain = _attr_chain(node)
            if chain and chain[0] in ("jax", "jnp"):
                out.append(Finding(
                    "R004", rel, node.lineno, scopes.context_of(node),
                    _snippet(src_lines, node),
                    "chaos hooks must not touch jax values"))
    return out


def _rule_r006(rel: str, tree: ast.Module, scopes: _Scopes,
               src_lines: List[str]) -> List[Finding]:
    if not any(seg in rel.split("/") for seg in ("serve", "fleet",
                                                 "resil", "mesh")):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain[:1] == ["threading"] and chain[-1] in (
                "Lock", "RLock", "Condition"):
            out.append(Finding(
                "R006", rel, node.lineno, scopes.context_of(node),
                _snippet(src_lines, node),
                f"bare threading.{chain[-1]} in a threaded subsystem — "
                "use analysis.locks.AuditedLock/AuditedRLock/"
                "AuditedCondition so HEAT2D_LOCK_AUDIT sees it"))
    return out


# ------------------------------------------------------------------ #
# R005: metric/doc drift (cross-file)
# ------------------------------------------------------------------ #

def _code_metric_names(trees: Dict[str, ast.Module]) -> Tuple[
        Dict[str, Tuple[str, int]], Set[str]]:
    """(literal name -> (file, line), wildcard suffixes). A metric
    instantiated with ``prefix + "_suffix"`` contributes a wildcard —
    checked loosely (some doc name must end with the suffix)."""
    names: Dict[str, Tuple[str, int]] = {}
    wildcards: Set[str] = set()

    def note(value, rel, lineno) -> None:
        if isinstance(value, str) and METRIC_RE.match(value):
            names.setdefault(value, (rel, lineno))

    for rel, tree in trees.items():
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                # metric names as parameter defaults (the
                # ``counter="serve_coalesced_total"`` pattern) — only
                # for parameters NAMED like a metric slot (a "prefix"
                # default is a family prefix, not a family)
                pos = node.args.posonlyargs + node.args.args
                for prm, d in zip(pos[len(pos)
                                      - len(node.args.defaults):],
                                  node.args.defaults):
                    if prm.arg in METRIC_KEYWORDS and isinstance(
                            d, ast.Constant):
                        note(d.value, rel, d.lineno)
                for prm, d in zip(node.args.kwonlyargs,
                                  node.args.kw_defaults):
                    if d is not None and prm.arg in METRIC_KEYWORDS \
                            and isinstance(d, ast.Constant):
                        note(d.value, rel, d.lineno)
                continue
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg in METRIC_KEYWORDS and isinstance(
                        kw.value, ast.Constant):
                    note(kw.value.value, rel, kw.value.lineno)
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in METRIC_METHODS
                    and node.args):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant):
                note(arg.value, rel, node.lineno)
            elif (isinstance(arg, ast.BinOp)
                    and isinstance(arg.op, ast.Add)
                    and isinstance(arg.right, ast.Constant)
                    and isinstance(arg.right.value, str)):
                wildcards.add(arg.right.value)
    return names, wildcards


_DOC_METRIC_RE = re.compile(
    r"`((?:serve|fleet|resil|tune|inverse|slo|load|control|mesh|adi|mg"
    r"|perf|problem|ir|analysis|autoscale|dist)_[a-z0-9_*]+)"
    r"(?:\{[^`]*\})?`")


def _doc_metric_names(docs_dir: str) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    if not os.path.isdir(docs_dir):
        return out
    for fname in sorted(os.listdir(docs_dir)):
        if not fname.endswith(".md"):
            continue
        path = os.path.join(docs_dir, fname)
        with open(path) as f:
            for i, line in enumerate(f, 1):
                for m in _DOC_METRIC_RE.finditer(line):
                    name = m.group(1)
                    if name.endswith("_"):
                        # brace-expansion shorthand in the docs
                        # (``fleet_cache_{size,hit_rate}``): a prefix
                        # wildcard
                        name += "*"
                    out.setdefault(name, (f"docs/{fname}", i))
    return out


def _rule_r005(trees: Dict[str, ast.Module],
               docs_dir: str) -> List[Finding]:
    code, code_wild = _code_metric_names(trees)
    docs = _doc_metric_names(docs_dir)
    doc_exact = {n for n in docs if "*" not in n}
    doc_prefixes = {n.rstrip("*") for n in docs if "*" in n}
    out: List[Finding] = []

    def doc_covers(name: str) -> bool:
        return name in doc_exact or any(
            name.startswith(p) for p in doc_prefixes)

    for name, (rel, line) in sorted(code.items()):
        if not doc_covers(name):
            out.append(Finding(
                "R005", rel, line, "metrics", name,
                f"metric family {name!r} is instantiated here but "
                "appears in no docs/*.md table"))

    code_exact = set(code)

    def code_covers(name: str) -> bool:
        if "*" in name:
            prefix = name.rstrip("*")
            # a doc wildcard is satisfied by any literal under the
            # prefix; dynamically-prefixed families (code wildcards)
            # can't be resolved statically — benefit of the doubt
            return (any(c.startswith(prefix) for c in code_exact)
                    or bool(code_wild))
        return (name in code_exact
                or any(name.endswith(s) for s in code_wild))

    for name, (rel, line) in sorted(docs.items()):
        if not code_covers(name):
            out.append(Finding(
                "R005", rel, line, "metrics", name,
                f"documented metric family {name!r} is never "
                "instantiated in code"))
    return out


# ------------------------------------------------------------------ #
# driver
# ------------------------------------------------------------------ #

def lint_tree(root: str, rules: Optional[Iterable[str]] = None,
              docs_dir: Optional[str] = None) -> List[Finding]:
    """Run the selected rules over every ``*.py`` under ``root``
    (tests/ excluded) plus the docs drift check. Returns findings
    sorted by (path, line)."""
    active = set(rules) if rules is not None else set(ALL_RULES)
    unknown = active - set(ALL_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)}")
    trees: Dict[str, ast.Module] = {}
    sources: Dict[str, List[str]] = {}
    findings: List[Finding] = []
    for path in _iter_py_files(root):
        rel = _relpath(path, root)
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            findings.append(Finding(
                "R000", rel, e.lineno or 0, "<module>", "syntax-error",
                f"file does not parse: {e.msg}"))
            continue
        trees[rel] = tree
        sources[rel] = src.splitlines()

    bound = registry_bound_names(trees) if active & {"R002", "R003"} \
        else set()
    for rel, tree in trees.items():
        scopes = _Scopes(tree)
        lines = sources[rel]
        if "R001" in active:
            findings.extend(_rule_r001(rel, tree, scopes, lines))
        if active & {"R002", "R003"}:
            findings.extend(_rule_r002_r003(rel, tree, scopes, lines,
                                            active,
                                            extra_roots=bound))
        if "R004" in active:
            findings.extend(_rule_r004(rel, tree, scopes, lines))
        if "R006" in active:
            findings.extend(_rule_r006(rel, tree, scopes, lines))

    if "R005" in active:
        findings.extend(_rule_r005(
            trees, docs_dir if docs_dir is not None
            else os.path.join(root, "docs")))

    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def split_baselined(findings: List[Finding],
                    baseline: Dict[str, str]) -> Tuple[
        List[Finding], List[Finding], List[str]]:
    """(new, grandfathered, stale-baseline-keys)."""
    new, old = [], []
    seen: Set[str] = set()
    for f in findings:
        if f.key in baseline:
            old.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    stale = [k for k in baseline if k not in seen]
    return new, old, stale
