"""``heat2d-tpu-lint`` — the zero-findings CI gate.

Runs the repo-specific rules (analysis/lint.py) over a tree and exits
rc 1 on any NEW finding (one not grandfathered in the baseline, with a
justification, at ``analysis/baseline.json``). ``--format json`` emits
machine-readable findings for tooling; stale baseline entries (the
finding was fixed but its entry lingers) are reported so the baseline
only ever shrinks deliberately.

``--ir`` switches from source lint to the jaxpr IR verifier
(analysis/ir.py): it traces every registered (family × route) batch
program plus the dist2d sharded programs on an 8-device simulated
mesh and checks the declared footprint / dtype / collective contracts
— rc 1 on any finding. This is the CI ``ir-gate`` entry point.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from heat2d_tpu.analysis import lint


def _default_root() -> str:
    """The tree to lint: cwd when it holds the package, else the
    installed package's parent (so the CLI works from anywhere)."""
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, "heat2d_tpu")):
        return cwd
    import heat2d_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(heat2d_tpu.__file__)))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def _run_ir(args) -> int:
    """The ``--ir`` mode: force the 8-device sim mesh BEFORE jax
    initializes a backend (the collective pass degrades gracefully on
    fewer devices but the gate wants the full sweep), then run the
    verifier and render findings."""
    from heat2d_tpu.utils.platform import force_host_devices

    force_host_devices(8)
    from heat2d_tpu.analysis import ir

    try:
        rep = ir.verify_all()
    except Exception as e:      # a crash must fail the gate loudly
        print(f"heat2d-tpu-lint --ir: verifier error: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in rep.findings],
            "footprint_rows": [
                {**r, "derived": list(r["derived"]) if r["derived"]
                 else None,
                 "witness": list(r["witness"]) if r["witness"]
                 else None}
                for r in rep.footprint_rows],
            "cards": [{"program": c.program,
                       "casts": [c2.describe() for c2 in c.casts]}
                      for c in rep.cards],
            "collectives": rep.collective_rows,
            "notes": rep.notes,
            "ok": rep.ok,
        }, indent=2))
    else:
        print(ir.render_report(rep, verbose=args.verbose))
    return 0 if rep.ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-lint",
        description="heat2d-tpu invariant linter (rules R001-R006)")
    p.add_argument("root", nargs="?", default=None,
                   help="tree to lint (default: the repo / installed "
                        "package root)")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset, e.g. R001,R006 "
                        "(default: all)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON of grandfathered findings "
                        "(default: analysis/baseline.json; 'none' "
                        "disables)")
    p.add_argument("--format", choices=("text", "json"),
                   default="text")
    p.add_argument("--docs", default=None,
                   help="docs directory for the drift rule "
                        "(default: <root>/docs)")
    p.add_argument("--ir", action="store_true",
                   help="run the jaxpr IR verifier (footprint, "
                        "dtype-flow, collective contracts) over every "
                        "registered program instead of the source "
                        "lint rules")
    p.add_argument("--verbose", action="store_true",
                   help="with --ir: print precision cards and "
                        "collective censuses, not just findings")
    args = p.parse_args(argv)

    if args.ir:
        return _run_ir(args)

    root = args.root or _default_root()
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if args.baseline == "none":
        baseline_path = None
    else:
        baseline_path = args.baseline or default_baseline_path()
    try:
        baseline = lint.load_baseline(baseline_path)
        findings = lint.lint_tree(root, rules=rules,
                                  docs_dir=args.docs)
    except (lint.BaselineError, ValueError) as e:
        print(f"heat2d-tpu-lint: {e}", file=sys.stderr)
        return 2
    new, grandfathered, stale = lint.split_baselined(findings, baseline)

    if args.format == "json":
        print(json.dumps({
            "root": os.path.abspath(root),
            "rules": sorted(rules) if rules else list(lint.ALL_RULES),
            "new": [f.to_dict() for f in new],
            "baselined": [
                f.to_dict() | {"justification": baseline[f.key]}
                for f in grandfathered],
            "stale_baseline_keys": stale,
            "ok": not new,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if grandfathered:
            print(f"# {len(grandfathered)} baselined finding(s) "
                  "suppressed:")
            for f in grandfathered:
                print(f"#   {f.key}\n#     justification: "
                      f"{baseline[f.key]}")
        for k in stale:
            print(f"# stale baseline entry (finding no longer "
                  f"present): {k}")
        print(f"{'FAIL' if new else 'OK'}: {len(new)} new finding(s), "
              f"{len(grandfathered)} baselined, {len(stale)} stale "
              "baseline entr(y/ies)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
