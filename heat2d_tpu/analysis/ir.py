"""Jaxpr IR verifier — footprint, dtype-flow, and collective contracts
for every registered program.

The problem registry made the spatial operator a declared contract
(``FamilySpec``: halo_width, reads_per_step, kernel_routes) and the
sharded path documents its communication schedule (4 ppermutes per
chunk, parallel/halo.py) — but until this module nothing checked the
declarations against the *traced programs*. Three passes close that
gap, all host-side (they trace with ``jax.make_jaxpr`` and never run a
program; the suite pins that tracing is observation-only):

1. **Footprint** (analysis/footprint.py): the offset-interval abstract
   interpreter derives each family kernel's true spatial access radius
   and asserts it equals the declared ``halo_width`` on every axis, for
   the reference step, the value-form kernel the Pallas/band templates
   trace, AND the traced band program's actual ghost-strip depth
   (``pallas_call`` operand shapes vs the shared ``band_plan``). The
   interpreter's coefficient-read count is the static witness for
   ``reads_per_step``, cross-checked against the roofline model's
   analytic jnp-stream bytes.
2. **Dtype-flow** (analysis/dtype_flow.py): a per-program precision
   card lists every cast with provenance; precision-relevant casts not
   on the family's declared ``cast_allowlist`` are findings.
3. **Collective contract**: the census of communication primitives in
   each shard_map program is checked against
   ``parallel.sharded.COLLECTIVE_CONTRACT`` (exactly 4 nearest-neighbor
   ppermutes per exchange, psum only for convergence, gather-family
   primitives forbidden), and every *non*-sharded batch program must
   contain no collectives at all — an injected ``all_gather`` is named
   with its provenance path.

``verify_all`` sweeps every registered (family × kernel route) batch
program plus the dist2d sharded programs (both halo routes, fixed-step
and convergence) on the simulated device mesh; ``heat2d-tpu-lint
--ir`` and the CI ``ir-gate`` job run it and require zero findings,
while the seeded-violation suite (tests/test_ir.py) proves each pass
fires.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

from heat2d_tpu.analysis.dtype_flow import (PrecisionCard, census_casts,
                                            census_collectives)
from heat2d_tpu.analysis.footprint import derive_footprint

PASS_FOOTPRINT = "footprint"
PASS_DTYPE = "dtype-flow"
PASS_COLLECTIVE = "collective"


@dataclasses.dataclass(frozen=True)
class IrFinding:
    """One contract violation in one traced program."""

    pass_name: str
    program: str
    message: str

    def describe(self) -> str:
        return f"[{self.pass_name}] {self.program}: {self.message}"


@dataclasses.dataclass
class IrReport:
    """The sweep's full output: findings (empty == gate passes) plus
    the derived-vs-declared evidence rows the CLI renders."""

    findings: List[IrFinding] = dataclasses.field(default_factory=list)
    #: program, declared w, derived radii, witness, derived reads
    footprint_rows: List[dict] = dataclasses.field(default_factory=list)
    cards: List[PrecisionCard] = dataclasses.field(default_factory=list)
    #: program, collective census summaries
    collective_rows: List[dict] = dataclasses.field(default_factory=list)
    notes: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def merge(self, other: "IrReport") -> None:
        self.findings.extend(other.findings)
        self.footprint_rows.extend(other.footprint_rows)
        self.cards.extend(other.cards)
        self.collective_rows.extend(other.collective_rows)
        self.notes.extend(other.notes)


# ------------------------------------------------------------------ #
# pass building blocks — each is independently drivable, so the
# seeded-violation tests exercise them against deliberately broken
# programs without touching the registry
# ------------------------------------------------------------------ #

def check_kernel_footprint(program: str, fn: Callable, u,
                           declared_width: int,
                           declared_reads: Optional[int] = None
                           ) -> Tuple[List[IrFinding], dict]:
    """Derive ``fn``'s footprint on state array ``u`` and compare to
    the declared halo width (and, when given, reads_per_step)."""
    findings: List[IrFinding] = []
    fp = derive_footprint(fn, u)
    row = {"program": program, "declared_width": declared_width,
           "derived": None, "witness": None,
           "derived_reads": None, "declared_reads": declared_reads}
    if not fp.derivable:
        findings.append(IrFinding(
            PASS_FOOTPRINT, program,
            f"footprint underivable: primitive {fp.top!r} escapes the "
            f"offset-interval domain (declared halo_width="
            f"{declared_width})"))
        return findings, row
    radii = fp.radii()
    row["derived"] = radii
    row["witness"] = tuple(fp.witness(a) for a in range(len(radii)))
    for axis, r in enumerate(radii):
        if r != declared_width:
            findings.append(IrFinding(
                PASS_FOOTPRINT, program,
                f"axis {axis}: derived access radius {r} != declared "
                f"halo_width {declared_width} (offsets "
                f"[{fp.lo[axis]}, {fp.hi[axis]}], widened by "
                f"primitive {fp.witness(axis)!r})"))
    derived_reads = 1 + fp.coef_reads
    row["derived_reads"] = derived_reads
    if declared_reads is not None and derived_reads != declared_reads:
        findings.append(IrFinding(
            PASS_FOOTPRINT, program,
            f"derived HBM reads/step {derived_reads} (state + "
            f"{fp.coef_reads} coefficient field(s)) != declared "
            f"reads_per_step {declared_reads}"))
    return findings, row


def check_band_strips(program: str, closed, expected_halo_rows: int,
                      halo_width: int) -> List[IrFinding]:
    """The band route's static halo witness: every ``pallas_call`` in
    the traced program ships ghost-row strips whose depth (operand
    shape on the strip axis) equals the shared band plan's
    ``halo_width * tsteps``."""
    findings: List[IrFinding] = []
    seen = 0
    for eqn in _walk(getattr(closed, "jaxpr", closed)):
        if eqn.primitive.name != "pallas_call":
            continue
        for var in eqn.invars:
            aval = getattr(var, "aval", None)
            shape = getattr(aval, "shape", None)
            if shape is None or len(shape) != 4:
                continue            # strips are (b, nblk, h, n)
            seen += 1
            h = shape[2]
            if h != expected_halo_rows:
                findings.append(IrFinding(
                    PASS_FOOTPRINT, program,
                    f"pallas_call ghost strip ships {h} rows, but the "
                    f"band plan requires halo_width*tsteps = "
                    f"{expected_halo_rows} (halo_width {halo_width})"))
    if seen == 0:
        findings.append(IrFinding(
            PASS_FOOTPRINT, program,
            "no pallas_call ghost strips found in the traced band "
            "program — strip-depth contract unverifiable"))
    return findings


def check_dtypes(program: str, closed,
                 allowlist: Sequence[Tuple[str, str]] = ()
                 ) -> Tuple[List[IrFinding], PrecisionCard]:
    """Precision card + findings for casts outside the allowlist."""
    card = PrecisionCard(program=program, casts=census_casts(closed))
    findings = [
        IrFinding(
            PASS_DTYPE, program,
            f"undeclared cast {c.describe()} — declare it in the "
            f"family's cast_allowlist or remove it")
        for c in card.findings(allowlist)]
    return findings, card


def check_collectives(program: str, closed, contract: dict,
                      require_exchange: bool = True
                      ) -> Tuple[List[IrFinding], dict]:
    """Check a shard_map program's collective census against the
    declared contract (parallel.sharded.COLLECTIVE_CONTRACT)."""
    findings: List[IrFinding] = []
    sites = census_collectives(closed)
    per_exchange = contract["ppermutes_per_exchange"]
    dist = contract["neighbor_distance"]
    total_pp = 0
    for s in sites:
        if s.prim in contract["forbidden"]:
            findings.append(IrFinding(
                PASS_COLLECTIVE, program,
                f"forbidden collective {s.describe()} — the halo "
                f"contract moves O(halo) bytes via ppermute only; a "
                f"{s.prim} moves O(grid) bytes per step"))
            continue
        if s.prim not in contract["allowed"]:
            findings.append(IrFinding(
                PASS_COLLECTIVE, program,
                f"undeclared collective {s.describe()} (allowed: "
                f"{contract['allowed']})"))
            continue
        if s.prim == "ppermute":
            total_pp += s.count
            if s.count % per_exchange:
                findings.append(IrFinding(
                    PASS_COLLECTIVE, program,
                    f"{s.describe()}: count is not a multiple of the "
                    f"{per_exchange}-ppermute exchange"))
            for a, b in s.perms:
                if abs(a - b) != dist:
                    findings.append(IrFinding(
                        PASS_COLLECTIVE, program,
                        f"ppermute pair ({a}, {b}) is not a nearest-"
                        f"neighbor shift (|src-dst| != {dist})"))
    if require_exchange and total_pp == 0:
        findings.append(IrFinding(
            PASS_COLLECTIVE, program,
            "no ppermute halo exchange found in the traced shard_map "
            "program"))
    row = {"program": program,
           "census": [s.describe() for s in sites],
           "ppermutes": total_pp}
    return findings, row


def check_no_collectives(program: str, closed
                         ) -> Tuple[List[IrFinding], dict]:
    """Single-host batch programs carry no collectives at all."""
    sites = census_collectives(closed)
    findings = [
        IrFinding(
            PASS_COLLECTIVE, program,
            f"unexpected collective {s.describe()} in a non-sharded "
            f"batch program")
        for s in sites]
    return findings, {"program": program,
                      "census": [s.describe() for s in sites],
                      "ppermutes": 0}


def _walk(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for s in vals:
                if hasattr(s, "jaxpr") and hasattr(s.jaxpr, "eqns"):
                    yield from _walk(s.jaxpr)
                elif hasattr(s, "eqns"):
                    yield from _walk(s)


# ------------------------------------------------------------------ #
# the registry sweep
# ------------------------------------------------------------------ #

_CX, _CY = 0.1, 0.1


def _verify_family(name: str, nx: int, ny: int, batch: int) -> IrReport:
    import jax
    import jax.numpy as jnp

    from heat2d_tpu.obs.roofline import analytic_bytes_per_cell_step
    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.problems.registry import get_family
    from heat2d_tpu.problems.runners import fixed_runner

    rep = IrReport()
    fam = get_family(name)
    spec = fam.spec
    w = spec.halo_width
    u = jnp.zeros((nx, ny), jnp.float32)

    # reference step kernel: radius + reads witness
    f, row = check_kernel_footprint(
        f"{name}/step", lambda v: fam.step(v, _CX, _CY), u, w,
        declared_reads=spec.reads_per_step)
    rep.findings.extend(f)
    rep.footprint_rows.append(row)

    # roofline cross-check: the analytic jnp-stream model must count
    # exactly the statically-derived HBM-touching operands (+1 write)
    if row["derived_reads"] is not None and "jnp" in spec.kernel_routes:
        model = analytic_bytes_per_cell_step(nx, ny, method="jnp",
                                             problem=name)
        expect = (row["derived_reads"] + 1) * 4.0   # float32
        if model["bytes_per_cell_step"] != expect:
            rep.findings.append(IrFinding(
                PASS_FOOTPRINT, f"{name}/roofline",
                f"roofline jnp model streams "
                f"{model['bytes_per_cell_step']}B/cell-step but the "
                f"derived operand count implies {expect}B "
                f"({row['derived_reads']} reads + 1 write)"))

    # value-form kernel: what the Pallas/band templates trace per step
    if any(r in spec.kernel_routes for r in ("pallas", "band")):
        scalars = fam.scalars(_CX, _CY)
        f, row = check_kernel_footprint(
            f"{name}/step_value",
            lambda v: fam.step_value(v, *scalars), u, w)
        rep.findings.extend(f)
        rep.footprint_rows.append(row)

    # per-route traced batch programs: precision card + no collectives
    u0 = jnp.zeros((batch, nx, ny), jnp.float32)
    cs = jnp.full((batch,), _CX, jnp.float32)
    for route in spec.kernel_routes:
        run = fixed_runner(name, route)
        if route == "band":
            plan = ps.band_plan(nx, ny, u0.dtype, halo_width=w)
            steps = plan.tsteps     # one whole sweep, no remainder
        else:
            steps = 8
        closed = jax.make_jaxpr(
            lambda a, b, c: run(a, b, c, steps=steps))(u0, cs, cs)
        prog = f"{name}/{route}"
        f, card = check_dtypes(prog, closed, spec.cast_allowlist)
        rep.findings.extend(f)
        rep.cards.append(card)
        f, crow = check_no_collectives(prog, closed)
        rep.findings.extend(f)
        rep.collective_rows.append(crow)
        if route == "band":
            rep.findings.extend(check_band_strips(
                prog, closed, plan.halo_rows, w))
    return rep


def _sharded_mesh_shape(n_devices: int) -> Optional[Tuple[int, int]]:
    if n_devices >= 8:
        return (2, 4)
    if n_devices >= 4:
        return (2, 2)
    if n_devices >= 2:
        return (1, 2)
    return None


def _verify_sharded(nx: int, ny: int) -> IrReport:
    import jax

    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.parallel.mesh import make_mesh
    from heat2d_tpu.parallel.sharded import (COLLECTIVE_CONTRACT,
                                             make_sharded_runner,
                                             resolve_halo_route,
                                             sharded_inidat)
    from heat2d_tpu.problems.base import spec_for

    rep = IrReport()
    shape = _sharded_mesh_shape(len(jax.devices()))
    if shape is None:
        rep.notes.append(
            "collective pass skipped: single-device runtime (run "
            "under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "for the full sweep)")
        return rep
    gx, gy = shape
    if shape != (2, 4):
        rep.notes.append(
            f"collective pass degraded to a {gx}x{gy} mesh "
            f"({len(jax.devices())} devices visible)")
    mesh = make_mesh(gx, gy)
    allow = spec_for("heat5").cast_allowlist
    for halo in ("collective", "fused"):
        for conv in (False, True):
            cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=12,
                             mode="dist2d", gridx=gx, gridy=gy,
                             halo_depth=3, halo=halo,
                             convergence=conv)
            tier = resolve_halo_route(cfg, mesh)["tier"]
            runner, _ = make_sharded_runner(cfg, mesh)
            fn = getattr(runner, "__wrapped__", runner)
            u0 = sharded_inidat(cfg, mesh)
            closed = jax.make_jaxpr(fn)(u0)
            prog = (f"sharded/{halo}[{tier}]/"
                    f"{'conv' if conv else 'fixed'}")
            f, crow = check_collectives(prog, closed,
                                        COLLECTIVE_CONTRACT)
            rep.findings.extend(f)
            rep.collective_rows.append(crow)
            f, card = check_dtypes(prog, closed, allow)
            rep.findings.extend(f)
            rep.cards.append(card)
    return rep


def verify_all(nx: int = 32, ny: int = 64, batch: int = 2,
               include_sharded: bool = True) -> IrReport:
    """The full IR gate: every registered family × kernel route batch
    program, plus the dist2d sharded programs on the simulated mesh.
    Zero findings == the declared contracts match the traced IR."""
    from heat2d_tpu.problems.registry import family_names

    rep = IrReport()
    for name in family_names():
        rep.merge(_verify_family(name, nx, ny, batch))
    if include_sharded:
        rep.merge(_verify_sharded(48, 48))
    return rep


def render_report(rep: IrReport, verbose: bool = False) -> str:
    """The CLI's human-readable rendering."""
    lines: List[str] = []
    lines.append("IR verification "
                 f"({len(rep.footprint_rows)} footprint rows, "
                 f"{len(rep.cards)} precision cards, "
                 f"{len(rep.collective_rows)} collective censuses)")
    for row in rep.footprint_rows:
        derived = (f"radii {row['derived']}" if row["derived"]
                   else "underivable")
        reads = ""
        if row["derived_reads"] is not None and \
                row["declared_reads"] is not None:
            reads = (f", reads {row['derived_reads']} "
                     f"(declared {row['declared_reads']})")
        lines.append(f"  {row['program']}: declared w="
                     f"{row['declared_width']}, derived {derived}"
                     f"{reads}")
    if verbose:
        for card in rep.cards:
            lines.extend("  " + ln for ln in card.lines())
        for row in rep.collective_rows:
            census = "; ".join(row["census"]) or "none"
            lines.append(f"  {row['program']}: collectives: {census}")
    for note in rep.notes:
        lines.append(f"  note: {note}")
    if rep.findings:
        lines.append(f"{len(rep.findings)} IR finding(s):")
        lines.extend("  " + f.describe() for f in rep.findings)
    else:
        lines.append("no IR findings — declared contracts match the "
                     "traced programs")
    return "\n".join(lines)
