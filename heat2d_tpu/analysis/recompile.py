"""Recompilation sentinel — bounded compile counts as a checked invariant.

The serving design leans hard on compile-count discipline: the
power-of-two batch padding exists so one signature compiles
O(log max_batch) programs, the memoized ``ensemble.batch_runner``
exists so steady-state traffic never retraces, and the fleet's warm
restart replays hot signatures precisely because a compile is the
expensive thing being restored. None of that was *checked* — a
weak_type flip, an unhashable static, or a dtype-promotion change in
a cache key silently turns O(log B) into O(requests), and the only
symptom is a slow soak.

``CompileWatch`` counts ACTUAL XLA compiles by listening to jax's
compile logs (``jax.log_compiles`` routes one "Finished XLA
compilation of <name>" record per backend compile through the
``jax._src.dispatch`` logger — backend-independent, CPU CI included).
``assert_bounded`` turns a watch into a gate; ``serve_compile_report``
drives a representative serve workload (every occupancy 1..max_batch
through ``EnsembleEngine``) and reports compiles per signature so the
O(log max_batch) contract is a test, not a comment.
"""

from __future__ import annotations

import logging
import math
import re
from typing import Any, Callable, Dict, List, Optional

#: the logger jax routes per-compile records through (stable across
#: the jax versions this repo supports; the regex below is the
#: contract, the logger name just the tap point)
_DISPATCH_LOGGER = "jax._src.dispatch"

#: sibling logger log_compiles also raises to WARNING ("Compiling <f>
#: with global shapes..."); silenced during a watch so tests stay quiet
_PXLA_LOGGER = "jax._src.interpreters.pxla"

_COMPILE_RE = re.compile(r"Finished XLA compilation of (.+?) in ")


class RecompileBudgetError(AssertionError):
    """A watched region compiled more programs than its budget — the
    cache-key blowup class the sentinel exists to catch."""


class _Capture(logging.Handler):
    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.names: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        m = _COMPILE_RE.search(record.getMessage())
        if m:
            self.names.append(m.group(1))


class CompileWatch:
    """Context manager counting XLA compiles inside its block.

    ``limit``: optional compile budget — exceeding it raises
    ``RecompileBudgetError`` at exit (with the offending program
    names). ``match``: only count programs whose logged name contains
    this substring / regex (``re.search``) — jax compiles tiny helper
    programs (``convert_element_type`` etc.) around any real workload,
    and a sentinel gating "the runner compiled once" must not count
    them against the budget.
    """

    def __init__(self, limit: Optional[int] = None,
                 match: Optional[str] = None):
        self.limit = limit
        self.match = match
        self._handler = _Capture()
        self._ctx = None

    # -- results -------------------------------------------------------

    @property
    def names(self) -> List[str]:
        """Logged program names, filtered by ``match``."""
        if self.match is None:
            return list(self._handler.names)
        pat = re.compile(self.match)
        return [n for n in self._handler.names if pat.search(n)]

    @property
    def count(self) -> int:
        return len(self.names)

    def counts_by_name(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for n in self.names:
            out[n] = out.get(n, 0) + 1
        return out

    # -- context -------------------------------------------------------

    def __enter__(self) -> "CompileWatch":
        import jax

        logger = logging.getLogger(_DISPATCH_LOGGER)
        self._prev_level = logger.level
        self._prev_propagate = logger.propagate
        # the log_compiles records are emitted at WARNING; make sure a
        # quieted logger still delivers them to OUR handler — and only
        # ours (propagation off keeps the console clean in tests)
        if logger.level > logging.WARNING:
            logger.setLevel(logging.WARNING)
        logger.propagate = False
        logger.addHandler(self._handler)
        pxla = logging.getLogger(_PXLA_LOGGER)
        self._prev_pxla_propagate = pxla.propagate
        pxla.propagate = False
        self._ctx = jax.log_compiles(True)
        self._ctx.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        logger = logging.getLogger(_DISPATCH_LOGGER)
        try:
            self._ctx.__exit__(exc_type, exc, tb)
        finally:
            logger.removeHandler(self._handler)
            logger.setLevel(self._prev_level)
            logger.propagate = self._prev_propagate
            logging.getLogger(_PXLA_LOGGER).propagate = \
                self._prev_pxla_propagate
        if exc_type is None and self.limit is not None \
                and self.count > self.limit:
            raise RecompileBudgetError(
                f"compile budget exceeded: {self.count} XLA compiles "
                f"(limit {self.limit})"
                + (f" matching {self.match!r}" if self.match else "")
                + f": {self.counts_by_name()}")


def assert_bounded(watch: CompileWatch, limit: int,
                   label: str = "workload") -> None:
    """Post-hoc budget check on a finished watch (for code that wants
    the report even on failure paths)."""
    if watch.count > limit:
        raise RecompileBudgetError(
            f"{label}: {watch.count} XLA compiles exceed the budget of "
            f"{limit}: {watch.counts_by_name()}")


def log2_capacity_budget(max_batch: int) -> int:
    """The serve contract: power-of-two padding means at most
    ``floor(log2(max_batch)) + 1`` distinct capacities — one compile
    each — per (signature, program) pair."""
    return int(math.floor(math.log2(max(1, max_batch)))) + 1


#: logged-name filter for the serve engine's batch runners (the
#: memoized jitted callables serve dispatches through; ensemble.
#: batch_runner stamps the name — mesh/spatial runners embed the same
#: stem, so one filter covers every engine flavor)
SERVE_RUNNER_MATCH = r"batch_runner"


def serve_compile_report(*, nx: int = 16, ny: int = 16, steps: int = 4,
                         method: str = "jnp", max_batch: int = 8,
                         convergence: bool = False,
                         engine_factory: Optional[Callable[[], Any]]
                         = None) -> dict:
    """Drive a representative serve workload — one signature, EVERY
    occupancy 1..max_batch through the engine's ``solve_batch`` —
    under a ``CompileWatch`` and report the compile accounting.

    Returns ``{"compiles": int, "budget": int, "names": {...},
    "launches": int, "capacities": [...]}`` — the caller (test or CI
    gate) asserts ``compiles <= budget``. The engine pads occupancies
    to powers of two, so the runner must compile once per DISTINCT
    capacity, never once per occupancy: O(log max_batch), the exact
    property the padding design bought.

    ``engine_factory``: builds the engine under report (default the
    single-chip ``EnsembleEngine``) — how the mesh gate proves the
    SAME contract holds per mesh config (``mesh.MeshEnsembleEngine``
    pads to device-multiple capacities: fewer rungs, never more
    compiles; its occupancies sweep 1..its own max_batch)."""
    from heat2d_tpu.models import ensemble
    from heat2d_tpu.serve.engine import EnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest

    # fresh runner caches: reusing an executable another test already
    # compiled would undercount and pass vacuously
    ensemble.batch_runner.cache_clear()
    ensemble.spatial_batch_runner.cache_clear()
    try:
        from heat2d_tpu.mesh.runner import mesh_batch_runner
        mesh_batch_runner.cache_clear()
    except ImportError:  # pragma: no cover - partial install
        pass
    engine = (engine_factory() if engine_factory is not None
              else EnsembleEngine(max_batch=max_batch))
    max_occupancy = min(max_batch, engine.max_batch)
    with CompileWatch(match=SERVE_RUNNER_MATCH) as watch:
        for occupancy in range(1, max_occupancy + 1):
            reqs = [SolveRequest(nx=nx, ny=ny, steps=steps,
                                 cx=0.1 + 0.01 * i, cy=0.1,
                                 method=method,
                                 convergence=convergence)
                    for i in range(occupancy)]
            engine.solve_batch(reqs)
    capacities = sorted({row["capacity"] for row in engine.launch_log})
    return {
        "compiles": watch.count,
        "budget": log2_capacity_budget(max_occupancy),
        "names": watch.counts_by_name(),
        "launches": engine.launches,
        "capacities": capacities,
    }
