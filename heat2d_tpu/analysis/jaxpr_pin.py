"""Consolidated jaxpr-pin library — ONE copy of the "this subsystem is
free when off" proof pattern.

Nearly every PR since the obs subsystem carries the same acceptance
pin: trace a hot-path program with the new subsystem exercised/armed
and again without it, and assert the two jaxprs are byte-identical —
differentiability, tracing, tuning hooks, chaos, and the lock audit
all cost *nothing* on the compiled path. The helpers lived as
copy-pasted ``_solver_jaxpr``/``_batch_runner_jaxpr`` functions in five
test modules; this module is the single import (tests/_pin.py re-exports
for the suite), and ``assert_jaxpr_equal`` upgrades the bare ``==``
assert to a readable structural diff when a pin ever breaks.
"""

from __future__ import annotations

import difflib
from typing import Optional


def jaxpr_text(fn, *args, **kwargs) -> str:
    """``str(jax.make_jaxpr(fn)(*args))`` — the pinned representation.
    String form on purpose: the pins assert BYTE-identity of the traced
    program, and the printed jaxpr is the canonical stable text."""
    import jax

    return str(jax.make_jaxpr(fn)(*args, **kwargs))


def diff_jaxprs(a: str, b: str, label_a: str = "before",
                label_b: str = "after", context: int = 3) -> str:
    """Unified structural diff of two jaxpr texts (line-based; the
    printed jaxpr is one equation per line, so the diff reads as
    "which equations moved")."""
    return "\n".join(difflib.unified_diff(
        a.splitlines(), b.splitlines(),
        fromfile=label_a, tofile=label_b, n=context, lineterm=""))


def assert_jaxpr_equal(a: str, b: str, label: str = "jaxpr",
                       label_a: str = "before",
                       label_b: str = "after") -> None:
    """Byte-identity assert with a readable structural diff on
    mismatch — replaces the suite's bare ``assert before == after``
    (which printed two multi-thousand-line strings)."""
    if a == b:
        return
    al, bl = a.splitlines(), b.splitlines()
    d = diff_jaxprs(a, b, label_a, label_b)
    changed = sum(1 for ln in d.splitlines()
                  if ln[:1] in "+-" and ln[:3] not in ("+++", "---"))
    raise AssertionError(
        f"{label}: traced programs differ ({len(al)} vs {len(bl)} "
        f"equations, {changed} changed lines):\n{d}")


def assert_jaxpr_differs(a: str, b: str, label: str = "jaxpr") -> None:
    """Non-vacuity twin: assert the two programs actually differ
    (pinning two copies of the same bug to each other proves nothing)."""
    if a != b:
        return
    raise AssertionError(
        f"{label}: traced programs are byte-identical but were "
        "expected to differ — the pinned change is vacuous")


# ------------------------------------------------------------------ #
# the standard hot-path pins (shared by five test modules)
# ------------------------------------------------------------------ #

def solver_jaxpr(nx: int = 12, ny: int = 12, steps: int = 8,
                 mode: str = "serial", **cfg_kwargs) -> str:
    """The forward solver runner's program — THE pin for "subsystem X
    does not touch the serial hot path"."""
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver
    from heat2d_tpu.ops.init import inidat

    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode=mode,
                     **cfg_kwargs)
    u0 = inidat(nx, ny)
    return jaxpr_text(Heat2DSolver(cfg).make_runner(), u0)


def _cxys(b: int):
    import jax.numpy as jnp

    return jnp.asarray([0.1 + 0.1 * i for i in range(b)], jnp.float32)


def batch_runner_jaxpr(nx: int = 16, ny: int = 16, steps: int = 4,
                       method: str = "jnp", b: int = 2,
                       problem: Optional[str] = None) -> str:
    """The serve compile cache's memoized batch runner's program.
    ``problem`` (None = don't name the axis at all) lets the problem-
    registry pins compare the explicitly-threaded heat5 program to the
    pre-registry call shape."""
    import jax.numpy as jnp

    from heat2d_tpu.models import ensemble

    if problem is None:
        fn = ensemble.batch_runner(nx, ny, steps, method)
    else:
        fn = ensemble.batch_runner(nx, ny, steps, method,
                                   problem=problem)
    u0 = jnp.zeros((b, nx, ny), jnp.float32)
    cxs = _cxys(b)
    return jaxpr_text(fn, u0, cxs, cxs)


def band_runner_jaxpr(nx: int = 64, ny: int = 128, steps: int = 10,
                      b: int = 2) -> str:
    """The batched band kernel runner's program (the serve kernel path
    for HBM-sized members)."""
    import jax.numpy as jnp

    from heat2d_tpu.models.ensemble import _run_batch_band

    u0 = jnp.zeros((b, nx, ny), jnp.float32)
    cxs = _cxys(b)
    return jaxpr_text(lambda u, a, c: _run_batch_band(u, a, c,
                                                      steps=steps),
                      u0, cxs, cxs)


def mesh_runner_jaxpr(nx: int = 16, ny: int = 16, steps: int = 4,
                      method: str = "jnp", b: Optional[int] = None,
                      n_devices: Optional[int] = None,
                      abft: bool = False,
                      problem: str = "heat5") -> str:
    """The mesh-sharded serve batch runner's program (heat2d_tpu/
    mesh/runner.py) — pins that the scheduler/admission/fault layers
    are pure host-side math: the traced mesh program is identical
    with and without them armed (incl. an armed chaos device
    campaign). ``abft=True`` traces the checksum-verify variant — a
    DIFFERENT program by design (its non-vacuity twin), memoized under
    its own cache key so the default stays byte-identical."""
    import jax.numpy as jnp

    from heat2d_tpu.mesh.runner import mesh_batch_runner

    run = mesh_batch_runner(nx, ny, steps, method,
                            n_devices=n_devices, abft=abft,
                            problem=problem)
    b = b if b is not None else run.n_devices
    u0 = jnp.zeros((b, nx, ny), jnp.float32)
    cxs = _cxys(b)
    return jaxpr_text(run.jitted, u0, cxs, cxs)


def spatial_runner_jaxpr(nx: int = 24, ny: int = 24, steps: int = 8,
                         gridx: int = 1, gridy: int = 1,
                         halo: str = "collective",
                         n_devices: Optional[int] = None) -> str:
    """The memoized batch x spatial serve runner's program
    (``ensemble.spatial_batch_runner``) — the fused-vs-collective
    route pins compare the SERVE path through this (the degraded
    fused program must be byte-identical to the collective one, and a
    viable fused program must differ — non-vacuity)."""
    import jax.numpy as jnp

    from heat2d_tpu.models import ensemble

    run = ensemble.spatial_batch_runner(nx, ny, steps, gridx, gridy,
                                        halo=halo,
                                        n_devices=n_devices)
    meta = run.meta
    u0 = jnp.zeros((meta.nb, meta.pnx, meta.pny), jnp.float32)
    c = jnp.zeros((meta.nb,), jnp.float32)
    return jaxpr_text(run.jitted, u0, c, c)


def sharded_runner_jaxpr(cfg, mesh) -> str:
    """A dist2d/sharded multi-step runner's program on ``mesh`` (the
    fused-halo pins compare routes through this)."""
    from heat2d_tpu.parallel.sharded import (make_sharded_runner,
                                             sharded_inidat)

    u0 = sharded_inidat(cfg, mesh)
    runner, _ = make_sharded_runner(cfg, mesh)
    fn = getattr(runner, "__wrapped__", runner)
    return jaxpr_text(fn, u0)
