"""The CI ``implicit-gate`` — the implicit-route acceptance, as a
program (``python -m heat2d_tpu.analysis.implicit_gate``).

Four legs, every one an ISSUE-14 acceptance criterion:

1. **Algorithmic speed**: ADI reaches a fixed ``t_final`` at matched
   L2 accuracy (vs the analytic separable-mode solution) in >= 100x
   fewer steps and >= 10x lower MODELED wall-clock than the explicit
   scheme (``models/solution.py`` — the model is deterministic, so
   the verdict does not ride CI host jitter; measured wall-clock is
   printed beside it).
2. **Serve repeatability**: a ``method="adi"`` bucket answers
   bitwise-identically across independent engines AND across launch
   capacities (the pad-parity contract every explicit route already
   carries).
3. **Mesh parity**: on the host-simulated 8-device mesh, the
   mesh-sharded runner's ADI answers are bitwise the single-chip
   runner's (the route rides the PR 13 machinery unchanged).
4. **Compile ladder**: the recompile sentinel proves the
   O(log max_batch) padded-capacity contract holds for BOTH new
   routes (``analysis/recompile.serve_compile_report``).

Exit 0 iff every leg passes; failures print as ``FAIL: ...`` lines.
"""

from __future__ import annotations

import sys


def run_gate(out=sys.stdout) -> int:
    import numpy as np

    failures = []

    def check(name, ok, detail=""):
        line = f"{'PASS' if ok else 'FAIL'}: {name}"
        if detail:
            line += f" ({detail})"
        print(line, file=out if ok else sys.stderr)
        if not ok:
            failures.append(name)

    # -- leg 1: wall-clock-to-solution at matched accuracy ----------- #
    from heat2d_tpu.models import solution

    tts = solution.time_to_solution(
        257, 257, steps_explicit=2560, step_ratio=256,
        methods=("explicit", "adi"))
    s = tts["summary"]
    check("adi >= 100x fewer steps",
          s["adi_steps_ratio"] >= 100.0,
          f"ratio {s['adi_steps_ratio']:.0f}x")
    check("adi >= 10x modeled wall-clock-to-solution",
          s["adi_modeled_speedup"] >= 10.0,
          f"modeled {s['adi_modeled_speedup']:.1f}x, measured "
          f"{s['adi_wall_speedup']:.1f}x")
    rows = {r["method"]: r for r in tts["rows"]}
    check("adi matched L2 accuracy (f32)", s["adi_matched_accuracy"],
          f"adi {rows['adi']['accuracy']:.3e} vs explicit "
          f"{rows['explicit']['accuracy']:.3e}")
    # The f64 twin separates the algorithms from f32 roundoff: here
    # truncation dominates, and the O(dt^2) leg must sit STRICTLY at
    # or below the O(dt) leg's error despite 256x fewer steps.
    tts64 = solution.time_to_solution(
        257, 257, steps_explicit=2560, step_ratio=256,
        methods=("explicit", "adi"), dtype=np.float64)
    r64 = {r["method"]: r for r in tts64["rows"]}
    check("adi <= explicit L2 error (f64, truncation-dominated)",
          r64["adi"]["accuracy"] <= r64["explicit"]["accuracy"],
          f"adi {r64['adi']['accuracy']:.3e} vs explicit "
          f"{r64['explicit']['accuracy']:.3e}")

    # -- leg 2: serve-route bitwise repeatability -------------------- #
    from heat2d_tpu.serve.engine import EnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest

    req = SolveRequest(nx=24, ny=32, steps=4, cx=8.0, cy=6.0,
                       method="adi")
    twin = SolveRequest(nx=24, ny=32, steps=4, cx=3.0, cy=2.0,
                        method="adi")
    a = EnsembleEngine(max_batch=8).solve_batch([req])[0]
    b = EnsembleEngine(max_batch=8).solve_batch([req])[0]
    check("adi answers bitwise-repeatably across engines",
          np.asarray(a[0]).tobytes() == np.asarray(b[0]).tobytes())
    # Different occupancy -> different padded capacity -> a different
    # compiled program; pad parity must keep the member bitwise.
    c = EnsembleEngine(max_batch=8).solve_batch([req, twin])[0]
    check("adi bitwise across launch capacities",
          np.asarray(a[0]).tobytes() == np.asarray(c[0]).tobytes())

    # -- leg 3: mesh parity on the sim mesh -------------------------- #
    import jax

    nd = len(jax.devices())
    if nd >= 2:
        import jax.numpy as jnp

        from heat2d_tpu.mesh.runner import mesh_batch_runner, \
            mesh_capacity
        from heat2d_tpu.models import ensemble

        b_ = mesh_capacity(nd, 4 * nd, nd)
        u0 = jnp.broadcast_to(
            jnp.asarray(np.random.default_rng(14).normal(
                size=(24, 32)).astype(np.float32)), (b_, 24, 32))
        cxs = jnp.asarray([4.0 + i for i in range(b_)], jnp.float32)
        cys = jnp.asarray([2.0 + i for i in range(b_)], jnp.float32)
        mesh_run = mesh_batch_runner(24, 32, 4, "adi")
        single = ensemble.batch_runner(24, 32, 4, "adi")
        got = np.asarray(mesh_run(u0, cxs, cys))
        want = np.asarray(single(u0, cxs, cys))
        check(f"mesh({nd} devices) adi bitwise == single-chip",
              got.tobytes() == want.tobytes())
    else:
        check("mesh adi parity", True, "skipped: 1 device")

    # -- leg 4: the compile ladder for both routes ------------------- #
    from heat2d_tpu.analysis.recompile import serve_compile_report

    for method in ("adi", "mg"):
        rep = serve_compile_report(method=method, max_batch=8)
        check(f"{method} compile ladder O(log max_batch)",
              rep["compiles"] <= rep["budget"],
              f"{rep['compiles']} compiles <= budget {rep['budget']}, "
              f"capacities {rep['capacities']}")

    print(("implicit-gate FAILED" if failures else
           "implicit-gate passed"), file=out)
    return 1 if failures else 0


def main(argv=None) -> int:
    # x64 for the truncation-dominated f64 accuracy leg (f32 arrays
    # stay f32 — the flag only unlocks the wider dtype).
    import jax

    jax.config.update("jax_enable_x64", True)
    return run_gate()


if __name__ == "__main__":
    sys.exit(main())
