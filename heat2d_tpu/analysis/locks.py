"""Audited locks — instrumented drop-ins for ``threading.Lock``/``RLock``
with lock-order and guarded-state checking.

The serve/fleet/resil layers are threaded: batcher scheduler, fleet
reader/monitor threads, the async checkpoint writer. Their correctness
rests on two conventions this module turns into machine-checked
invariants:

1. **Lock ordering.** Every ``acquire`` of an audited lock while another
   audited lock is held records a directed edge (held -> acquired) in a
   global acquisition graph. ``report()`` runs cycle detection over the
   graph: a cycle is a potential deadlock (thread 1 takes A then B,
   thread 2 takes B then A — each can block the other forever). The
   check is *order-based*, so it fires even when the interleaving that
   would actually deadlock never happened in the run being audited.
2. **Guarded state.** ``@guarded_by("_lock", "attr", ...)`` registers
   which attributes of a class the named lock protects. Under audit,
   registered classes get a checking ``__setattr__``: a write to a
   guarded attribute without the owning lock held by the current thread
   is recorded as a violation. Writes before the lock has ever been
   held are exempt (``__init__`` publishes the object; until another
   thread can see it there is nothing to guard).

**Zero overhead when off**: ``AuditedLock()``/``AuditedRLock()`` are
factories that return *plain* ``threading.Lock``/``RLock`` objects
unless an auditor is installed (``install()`` or ``HEAT2D_LOCK_AUDIT=1``
in the environment), and ``guarded_by`` only records the registry —
``__setattr__`` is patched in at ``install()`` and restored at
``uninstall()``. The jaxpr pins in tests/test_analysis.py additionally
prove the audit cannot change a compiled program (it is host-side
bookkeeping only, like every obs hook).

Opt-in pytest wiring: ``HEAT2D_LOCK_AUDIT=1 pytest tests/test_serve.py
tests/test_fleet.py tests/test_resil.py`` runs the existing threaded
suites under audit — tests/conftest.py installs a per-test auditor and
fails the test on any violation or cycle (the CI ``lock-audit`` job).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple, Union

ENV_VAR = "HEAT2D_LOCK_AUDIT"

_TRUE = ("1", "true", "on", "yes")


def _env_enabled() -> bool:
    return os.environ.get(ENV_VAR, "").strip().lower() in _TRUE


# ------------------------------------------------------------------ #
# per-thread held-lock stack
# ------------------------------------------------------------------ #

_tls = threading.local()


def _held_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


# ------------------------------------------------------------------ #
# the auditor
# ------------------------------------------------------------------ #

class Violation:
    """One guarded-state write without the owning lock held."""

    __slots__ = ("cls", "attr", "lock_attr", "thread", "where")

    def __init__(self, cls: str, attr: str, lock_attr: str,
                 thread: str, where: str):
        self.cls = cls
        self.attr = attr
        self.lock_attr = lock_attr
        self.thread = thread
        self.where = where

    def __repr__(self) -> str:
        return (f"guarded-write: {self.cls}.{self.attr} written without "
                f"{self.cls}.{self.lock_attr} held (thread "
                f"{self.thread}) at {self.where}")


class AuditReport:
    """Snapshot of what an audit saw: the acquisition-order edges, any
    lock-order cycles (potential deadlocks), and any guarded-state
    violations."""

    def __init__(self, edges: Dict[Tuple[int, int], dict],
                 cycles: List[List[str]],
                 violations: List[Violation]):
        self.edges = edges
        self.cycles = cycles
        self.violations = violations

    @property
    def clean(self) -> bool:
        return not self.cycles and not self.violations

    def render(self) -> str:
        lines = []
        if self.cycles:
            lines.append(f"{len(self.cycles)} lock-order cycle(s) "
                         "(potential deadlock):")
            for cyc in self.cycles:
                lines.append("  " + " -> ".join(cyc + [cyc[0]]))
        if self.violations:
            lines.append(f"{len(self.violations)} guarded-state "
                         "violation(s):")
            for v in self.violations:
                lines.append("  " + repr(v))
        if not lines:
            lines.append("lock audit clean: "
                         f"{len(self.edges)} order edge(s), no cycles, "
                         "no guarded-state violations")
        return "\n".join(lines)


class LockAuditor:
    """Collects acquisition edges and guarded-write violations. One per
    ``install()``; all audited locks created while it is active feed it."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        #: (id(a), id(b)) -> {"names": (a, b), "thread": name}
        self.edges: Dict[Tuple[int, int], dict] = {}
        #: id -> lock name (nodes of the order graph)
        self.names: Dict[int, str] = {}
        self.violations: List[Violation] = []

    # -- recording ------------------------------------------------- #

    def note_acquire(self, lock: "_AuditedBase") -> None:
        held = _held_stack()
        if not held:
            return
        prev = held[-1]
        if prev is lock:            # re-entrant acquire: no ordering
            return
        key = (id(prev), id(lock))
        with self._mu:
            self.names[id(prev)] = prev.name
            self.names[id(lock)] = lock.name
            if key not in self.edges:
                self.edges[key] = {
                    "names": (prev.name, lock.name),
                    "thread": threading.current_thread().name,
                }

    def note_guard_violation(self, obj: object, attr: str,
                             lock_attr: str) -> None:
        # [-1] is this method, [-2] the patched __setattr__, [-3] the
        # actual write site.
        frames = traceback.extract_stack(limit=4)
        frame = frames[-3] if len(frames) >= 3 else frames[0]
        where = f"{frame.filename}:{frame.lineno}"
        with self._mu:
            self.violations.append(Violation(
                type(obj).__name__, attr, lock_attr,
                threading.current_thread().name, where))

    # -- analysis --------------------------------------------------- #

    def cycles(self) -> List[List[str]]:
        """Cycles in the acquisition-order graph, as lock-name lists.
        Iterative DFS with an on-stack set (the classic back-edge
        detection); each cycle reported once."""
        with self._mu:
            adj: Dict[int, Set[int]] = {}
            for (a, b) in self.edges:
                adj.setdefault(a, set()).add(b)
            names = dict(self.names)
        out: List[List[str]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in adj}
        for root in list(adj):
            if color.get(root, WHITE) != WHITE:
                continue
            stack: List[Tuple[int, list]] = [(root, list(adj.get(root, ())))]
            color[root] = GRAY
            path = [root]
            while stack:
                node, nbrs = stack[-1]
                if nbrs:
                    nxt = nbrs.pop()
                    c = color.get(nxt, WHITE)
                    if c == GRAY:            # back edge: a cycle
                        i = path.index(nxt)
                        cyc = [names.get(n, f"lock@{n:x}")
                               for n in path[i:]]
                        canon = tuple(sorted(cyc))
                        if canon not in seen_cycles:
                            seen_cycles.add(canon)
                            out.append(cyc)
                    elif c == WHITE:
                        color[nxt] = GRAY
                        path.append(nxt)
                        stack.append((nxt, list(adj.get(nxt, ()))))
                else:
                    stack.pop()
                    color[node] = BLACK
                    path.pop()
        return out

    def report(self) -> AuditReport:
        with self._mu:
            edges = dict(self.edges)
            violations = list(self.violations)
        return AuditReport(edges, self.cycles(), violations)


# ------------------------------------------------------------------ #
# audited lock types
# ------------------------------------------------------------------ #

class _AuditedBase:
    """Shared acquire/release bookkeeping over a real lock object."""

    def __init__(self, name: Optional[str], raw) -> None:
        self.name = name or f"lock@{id(self):x}"
        self._raw = raw
        self._owner: Optional[int] = None
        self._count = 0
        #: guarded-write checks only arm once the lock has been held —
        #: before that the owning object is still being constructed
        self._ever_held = False

    # the Lock protocol ------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        # Edges go to the LIVE auditor, resolved per acquire: audited
        # locks can outlive an install(fresh=True) cycle (module-level
        # locks, objects built in an earlier test) — binding the
        # auditor at construction would feed a dead collector and hide
        # their cycles from report().
        a = _auditor
        if a is not None and self._owner != me:
            a.note_acquire(self)    # re-entrant paths skip the edge
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._count += 1
            self._ever_held = True
            _held_stack().append(self)
        return got

    def release(self) -> None:
        self._count -= 1
        if self._count == 0:
            self._owner = None
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is self:
                del st[i]
                break
        self._raw.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._raw.locked() if hasattr(self._raw, "locked") \
            else self._owner is not None

    # threading.Condition integration ---------------------------------

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    # audit surface ----------------------------------------------------

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class _AuditedLock(_AuditedBase):
    def __init__(self, name: Optional[str] = None):
        super().__init__(name, threading.Lock())


class _AuditedRLock(_AuditedBase):
    def __init__(self, name: Optional[str] = None):
        super().__init__(name, threading.RLock())


#: what an audited-lock factory may hand back
LockLike = Union[threading.Lock, _AuditedBase]


# ------------------------------------------------------------------ #
# install / factories
# ------------------------------------------------------------------ #

_auditor: Optional[LockAuditor] = None
_install_mu = threading.Lock()
#: classes registered via @guarded_by: cls -> (lock_attr, attrs)
_GUARDS: Dict[type, Tuple[str, frozenset]] = {}
#: original __setattr__ of patched classes (for uninstall)
_PATCHED: Dict[type, object] = {}


def enabled() -> bool:
    """True when an auditor is active (installed or armed via env)."""
    return _auditor is not None or _env_enabled()


def install(fresh: bool = True) -> LockAuditor:
    """Activate auditing: subsequent ``AuditedLock()`` calls return
    instrumented locks feeding the returned auditor, and every class
    registered with ``@guarded_by`` gets the checking ``__setattr__``.
    Idempotent unless ``fresh`` (default) — then a new collector starts."""
    global _auditor
    with _install_mu:
        if _auditor is None or fresh:
            _auditor = LockAuditor()
        _patch_guarded()
        return _auditor


def uninstall() -> None:
    """Deactivate auditing and restore every patched ``__setattr__``."""
    global _auditor
    with _install_mu:
        _auditor = None
        for cls, orig in _PATCHED.items():
            cls.__setattr__ = orig      # type: ignore[method-assign]
        _PATCHED.clear()


def report() -> AuditReport:
    """The active (or last-installed) auditor's findings; an empty
    report when auditing never ran."""
    a = _auditor
    if a is None:
        return AuditReport({}, [], [])
    return a.report()


def _active_auditor() -> Optional[LockAuditor]:
    """The installed auditor, auto-installing when the env var arms
    audit for a whole process tree (fleet workers inherit it)."""
    if _auditor is not None:
        return _auditor
    if _env_enabled():
        return install(fresh=False)
    return None


def AuditedLock(name: Optional[str] = None) -> LockLike:
    """A mutex: plain ``threading.Lock`` when audit is off (zero
    overhead), an instrumented drop-in when on."""
    a = _active_auditor()
    if a is None:
        return threading.Lock()
    return _AuditedLock(name)


def AuditedRLock(name: Optional[str] = None) -> LockLike:
    """Re-entrant variant of ``AuditedLock``."""
    a = _active_auditor()
    if a is None:
        return threading.RLock()
    return _AuditedRLock(name)


def AuditedCondition(name: Optional[str] = None) -> threading.Condition:
    """A ``threading.Condition`` over an audited mutex (plain when audit
    is off). ``wait``/``notify`` go through the stdlib Condition; only
    the underlying mutex is instrumented."""
    a = _active_auditor()
    if a is None:
        return threading.Condition()
    audited = _AuditedLock(name)
    return threading.Condition(audited)  # type: ignore[arg-type]


# ------------------------------------------------------------------ #
# @guarded_by
# ------------------------------------------------------------------ #

def guarded_by(lock_attr: str, *attrs: str):
    """Class decorator: declare that writes to ``attrs`` require
    ``self.<lock_attr>`` to be held. Pure registration — the class is
    returned unchanged; ``install()`` patches the check in and
    ``uninstall()`` removes it, so production code pays nothing."""
    if not attrs:
        raise ValueError("guarded_by needs at least one guarded attr")

    def deco(cls: type) -> type:
        _GUARDS[cls] = (lock_attr, frozenset(attrs))
        if _auditor is not None:        # installed mid-session
            _patch_guarded()
        return cls

    return deco


def _lock_of(obj) -> Optional[_AuditedBase]:
    """Resolve a guard object to its audited mutex: audited locks pass
    through, a Condition yields its underlying lock, anything else
    (plain lock — audit was off when the owner was built) is
    uncheckable and returns None."""
    if isinstance(obj, _AuditedBase):
        return obj
    inner = getattr(obj, "_lock", None)     # threading.Condition
    if isinstance(inner, _AuditedBase):
        return inner
    return None


def _patch_guarded() -> None:
    for cls, (lock_attr, attrs) in _GUARDS.items():
        if cls in _PATCHED:
            continue
        orig = cls.__setattr__

        def checking(self, key, value, _orig=orig, _lock_attr=lock_attr,
                     _attrs=attrs):
            if key in _attrs:
                a = _auditor
                if a is not None:
                    lk = _lock_of(getattr(self, _lock_attr, None))
                    if (lk is not None and lk._ever_held
                            and not lk.held_by_current_thread()):
                        a.note_guard_violation(self, key, _lock_attr)
            _orig(self, key, value)

        _PATCHED[cls] = orig
        cls.__setattr__ = checking      # type: ignore[method-assign]
