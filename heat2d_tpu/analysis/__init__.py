"""Static analysis + correctness tooling (docs/ANALYSIS.md).

Four engines and one CLI:

- ``analysis.lint`` — AST linter for the repo's hand-enforced
  conventions (rules R001-R006), gated in CI by ``heat2d-tpu-lint``
  (analysis/cli.py) at zero non-baselined findings.
- ``analysis.ir`` (+ ``footprint``, ``dtype_flow``) — jaxpr IR
  verifier: offset-interval footprint analysis, dtype cast census,
  and collective-contract checks over every registered program,
  gated in CI by ``heat2d-tpu-lint --ir`` at zero findings.
- ``analysis.locks`` — audited drop-in locks: lock-order inversion
  (deadlock-cycle) detection plus ``@guarded_by`` guarded-state
  checking, opt-in via ``HEAT2D_LOCK_AUDIT=1``, zero overhead off.
- ``analysis.recompile`` — recompilation sentinel: counts actual XLA
  compiles and gates the serve engine's O(log max_batch) contract.
- ``analysis.jaxpr_pin`` — the consolidated jaxpr-pin library the test
  suite's "free when off" proofs share.
"""

from heat2d_tpu.analysis.locks import (AuditedCondition, AuditedLock,
                                       AuditedRLock, guarded_by)

__all__ = ["AuditedCondition", "AuditedLock", "AuditedRLock",
           "guarded_by"]
