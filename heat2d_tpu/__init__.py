"""heat2d-tpu: a TPU-native 2D heat-equation stencil framework.

JAX/XLA/Pallas/shard_map re-design of the capabilities of patschris/Heat2D
(see SURVEY.md for the blueprint and BASELINE.md for the numbers to beat).
"""

__version__ = "0.1.0"

__all__ = ["HeatConfig", "ConfigError", "Heat2DSolver", "RunResult",
           "__version__"]


def __getattr__(name):
    # Lazy re-exports: keep `import heat2d_tpu` (and the CLI's --help path)
    # free of jax import cost.
    if name in ("HeatConfig", "ConfigError"):
        import heat2d_tpu.config as _c
        return getattr(_c, name)
    if name in ("Heat2DSolver", "RunResult"):
        from heat2d_tpu.models import solver as _s
        return getattr(_s, name)
    raise AttributeError(f"module 'heat2d_tpu' has no attribute {name!r}")
