"""Multi-host runtime bring-up — the MPI_Init/Comm_size/Comm_rank analogue.

The reference brings its world up with MPI_Init + Comm_size/Comm_rank
(grad1612_mpi_heat.c:42-44) under mpiexec, and tears down with
MPI_Finalize (:314). The TPU equivalent is ``jax.distributed.initialize``:
each host process connects to a coordinator, after which ``jax.devices()``
spans every chip in the slice/pod and the single-program shard_map code in
heat2d_tpu.parallel.sharded runs unchanged — collectives ride ICI within a
slice and DCN across slices, scheduled by XLA (no NCCL/MPI plumbing to
manage).

On TPU pods the coordinator/process-id/count triple is normally discovered
from the environment (TPU metadata), so ``initialize_distributed()`` with
no arguments is the common path; explicit arguments mirror the mpiexec
launch line for CPU/GPU-style bring-up.

This module stays the thin, dependency-free floor; the full pod
runtime GREW OUT of it into ``heat2d_tpu.dist`` (docs/DISTRIBUTED.md):
``dist/runtime.py`` wraps the same bring-up in a ``DistWorld``
topology object plus bounded KV barriers/heartbeats that turn a dead
peer into a named ``HostLostError``, and ``heat2d-tpu-dist`` is the
mpiexec-style launcher. New code should reach for ``dist``; the
helpers here remain the shared primitives both layers use.
"""

from __future__ import annotations

import jax

_initialized = False


def initialize_distributed(coordinator: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None,
                           force: bool = False) -> dict:
    """Bring up the multi-process runtime; returns the world description.

    Safe to call when single-process (no coordinator, no cluster env, and
    force=False): jax.distributed.initialize is skipped and the world is
    {1 process}. ``force=True`` initializes with whatever the environment
    provides (TPU pod metadata discovery). Idempotent within a process
    (MPI_Init's call-once rule, enforced here by a flag rather than an
    error).
    """
    global _initialized
    want_init = force or (coordinator is not None
                          or num_processes is not None
                          or process_id is not None)
    if want_init and not _initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
        _initialized = True
    return world_summary()


def world_summary() -> dict:
    """Comm_size/Comm_rank as structured data."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def gather_to_host(u):
    """Full array on this host as numpy — the MPI result-gather. Arrays
    spanning non-addressable devices allgather first (tiled: shards
    concatenate back into the global array); host arrays and replicated
    outputs convert directly. The one gather idiom every output path
    (solver.run, CLI text dumps, ensemble batches) shares.

    HEAT2D_FORBID_GATHER=1 (test tripwire): raise instead of
    allgathering a host-spanning array — the no-cross-host-gather tests
    (e.g. the device-resident periodic-checkpoint loop) run whole CLI
    flows under it to prove no code path falls back to a global gather.
    """
    import numpy as np
    if not getattr(u, "is_fully_addressable", True):
        import os
        if os.environ.get("HEAT2D_FORBID_GATHER"):
            raise RuntimeError(
                "cross-host allgather reached under HEAT2D_FORBID_GATHER "
                "(test tripwire): this flow was expected to stay "
                "per-shard/device-resident")
        from jax.experimental import multihost_utils
        u = multihost_utils.process_allgather(u, tiled=True)
    return np.asarray(u)


def shutdown_distributed() -> None:
    """MPI_Finalize analogue; no-op when never initialized."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False
