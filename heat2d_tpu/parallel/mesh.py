"""Device-mesh construction — the MPI Cartesian-topology analogue.

The reference builds a GRIDY×GRIDX non-periodic Cartesian communicator with
MPI_Cart_create and discovers N/S/E/W neighbor ranks with MPI_Cart_shift
(grad1612_mpi_heat.c:73-81). On TPU the same role is played by a
``jax.sharding.Mesh`` over ('x', 'y'): neighbors are implicit in the
``lax.ppermute`` permutations (heat2d_tpu/parallel/halo.py), and the
REORGANISATION reorder flag's job — placing neighboring ranks on
well-connected hardware — is done by ``jax.make_mesh``'s ICI-aware device
ordering.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def make_mesh(gridx: int, gridy: int = 1, devices=None,
              axis_names=("x", "y")) -> Mesh:
    """A (gridx, gridy) mesh; axis 'x' shards grid rows, 'y' columns.

    Validates device count the way grad1612_mpi_heat.c:54-59 validates
    comm_sz == GRIDX*GRIDY.
    """
    if devices is None:
        devices = jax.devices()
    need = gridx * gridy
    if len(devices) < need:
        raise ValueError(
            f"ERROR: the number of devices must be at least {need} "
            f"(gridx={gridx} * gridy={gridy}); have {len(devices)}.")
    try:
        # ICI-topology-aware ordering when available.
        return jax.make_mesh((gridx, gridy), axis_names,
                             devices=devices[:need])
    except TypeError:
        import numpy as np
        dev = np.asarray(devices[:need]).reshape(gridx, gridy)
        return Mesh(dev, axis_names)


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=None):
    """``shard_map`` across jax versions — the ONE place the version
    quirks live: jax>=0.6 moved it to the top level, and the replication-
    check kwarg was renamed ``check_rep`` -> ``check_vma`` along the way.
    The check must be disableable wherever a pallas_call or a telemetry
    debug_callback runs inside the shard (neither has a replication
    rule). Every call site uses this so all have identical version
    tolerance."""
    try:
        shard_map = jax.shard_map
    except AttributeError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map
    if check_vma is not None:
        for kw in ("check_vma", "check_rep"):
            try:
                return shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs,
                                 **{kw: check_vma})
            except TypeError:  # this jax spells the kwarg the other way
                continue
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def neighbor_table(gridx: int, gridy: int = 1) -> list[dict]:
    """Per-shard N/S/E/W neighbor map — the reference's DEBUG topology
    dump (grad1612_mpi_heat.c:170-175: under DEBUG each rank prints the
    neighbor ranks MPI_Cart_shift returned, with MPI_PROC_NULL = -1 at
    the non-periodic edges). Shard id is the row-major (x, y) mesh
    position — the same order ``mesh.devices.flat`` and the halo
    ppermute permutations use, so the printed ids are the actual
    exchange partners."""
    table = []
    for i in range(gridx):
        for j in range(gridy):
            rank = i * gridy + j
            table.append({
                "shard": rank, "x": i, "y": j,
                "north": rank - gridy if i > 0 else -1,
                "south": rank + gridy if i < gridx - 1 else -1,
                "west": rank - 1 if j > 0 else -1,
                "east": rank + 1 if j < gridy - 1 else -1,
            })
    return table


def mesh_devices_summary(mesh: Mesh) -> dict:
    """Device/topology introspection — the detailsGPU analogue
    (grad1612_cuda_heat.cu:24-37), as structured data."""
    devs = list(mesh.devices.flat)
    d0 = devs[0]
    info = {
        "mesh_shape": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "n_devices": len(devs),
        "device_kind": getattr(d0, "device_kind", "unknown"),
        "platform": getattr(d0, "platform", "unknown"),
    }
    try:
        stats = d0.memory_stats()
        if stats:
            info["bytes_limit"] = stats.get("bytes_limit")
            info["bytes_in_use"] = stats.get("bytes_in_use")
    except Exception:
        pass
    return info
