"""Ghost-cell (halo) exchange via ``lax.ppermute`` — the MPI halo analogue.

Replaces the reference's three halo mechanisms with one primitive:
- blocking MPI_Send/MPI_Recv of edge rows (mpi_heat2Dn.c:179-192),
- persistent non-blocking 4-neighbor requests (grad1612_mpi_heat.c:209-244),
- MPI derived row/column datatypes (grad1612_mpi_heat.c:139-144 — strided
  column views are unnecessary here; XLA materializes contiguous slices).

Non-periodic boundaries: MPI_Cart_shift on a non-periodic grid yields
MPI_PROC_NULL at the edges, so edge ranks' ghost cells keep their
initialized value 0 (grad1612_mpi_heat.c:150-161). ``lax.ppermute`` with a
*partial* permutation has exactly that semantics — devices not named as a
destination receive zeros — so the ghost ring at the domain edge is 0 by
construction, and the engine's global-boundary mask keeps those cells from
ever being written anyway.

"Persistence" (amortized request setup, MPI_Send_init) maps to jit: the
exchange is traced once and compiled into the step program. Comm/compute
overlap (grad1612_mpi_heat.c:233-259 inner/boundary split) comes in two
strengths (config.halo, docs/SCALING.md):

- ``collective`` — exchange-then-compute; overlap is delegated to XLA's
  latency-hiding scheduler, which may overlap the ppermute DMA with the
  interior update (SURVEY.md A.4) but pays a collective data dependency
  at every chunk boundary.
- ``fused`` — the inner/boundary split made EXPLICIT: the interior sweep
  (which needs no halo data) is traced with no data dependency on the
  edge strips, so edge communication and interior compute overlap by
  construction; the t-wide boundary frames are recomputed from the
  strips afterwards and stitched in (sharded.make_local_chunk's fused
  branch; on TPU the exchange additionally moves INTO the Pallas kernel
  as async remote copies — ops.pallas_stencil kernel F).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def shift_from_lower(x, axis_name: str, axis_size: int):
    """Each device receives ``x`` from its lower-index neighbor along
    ``axis_name`` (device 0 receives zeros). MPI analogue: the matched
    send-to-south/recv-from-north pair."""
    if axis_size == 1:
        return jnp.zeros_like(x)
    perm = [(i, i + 1) for i in range(axis_size - 1)]
    return lax.ppermute(x, axis_name, perm)


def shift_from_upper(x, axis_name: str, axis_size: int):
    """Each device receives ``x`` from its higher-index neighbor along
    ``axis_name`` (last device receives zeros)."""
    if axis_size == 1:
        return jnp.zeros_like(x)
    perm = [(i + 1, i) for i in range(axis_size - 1)]
    return lax.ppermute(x, axis_name, perm)


def exchange_halo_strips(u, ax: str, ay: str, gx: int, gy: int, t: int):
    """T-deep halo exchange as four STRIPS — ``(north, south, west, east)``
    for a (bm, bn) shard block, without materializing the extended block:
    north/south are (t, bn) ghost rows above/below; west/east are
    (bm+2t, t) ghost columns of the *vertically-extended* rows (they carry
    the corner data).

    The wide-halo trick: exchanging a t-deep ghost ring lets a shard
    advance t steps locally per exchange — 4 ppermutes per t steps instead
    of 4t (the distributed analogue of the Pallas temporal blocking, and
    the same fewer-bigger-messages trade MPI codes make when they widen
    ghost rings).

    Corners: a t-step dependency cone reaches diagonal neighbors for t>=2,
    so the exchange is two-phase — N/S strips first (full shard width),
    then E/W strips assembled from the vertically-extended edge columns
    (every shard computes the same SPMD program, so the E/W shift sees the
    neighbor's already-extended edge columns). Edge shards receive zeros
    (PROC_NULL semantics), firewalled each step by the engine's
    global-boundary mask.

    Only strip-sized arrays move through HBM here — the hybrid kernels
    assemble the extended block in VMEM (the round-2 hybrid path built the
    (bm+2t, bn+2t) block in HBM per chunk, three full-block round-trips
    the per-chip throughput paid for; VERDICT r2 weak #1).
    """
    north = shift_from_lower(u[-t:, :], ax, gx)
    south = shift_from_upper(u[:t, :], ax, gx)
    right_edge = jnp.concatenate(
        [north[:, -t:], u[:, -t:], south[:, -t:]], axis=0)
    left_edge = jnp.concatenate(
        [north[:, :t], u[:, :t], south[:, :t]], axis=0)
    west = shift_from_lower(right_edge, ay, gy)
    east = shift_from_upper(left_edge, ay, gy)
    return north, south, west, east


def fused_halo_viable(bm: int, bn: int, t: int) -> bool:
    """Geometry gate for the fused (overlap) halo route at depth ``t``
    on a (bm, bn) shard block: the interior/frame decomposition tiles
    the block iff each t-wide boundary frame fits without overlapping
    its opposite — ``bm >= 2t`` and ``bn >= 2t``. Deep halos relative
    to the shard (halo_depth > interior) and 1-wide shards fail this
    and DEGRADE to the collective route (the route never errors; the
    deep-halo chunking tests pin the fallback bitwise)."""
    return t >= 1 and bm >= 2 * t and bn >= 2 * t


def exchange_halo_2d_wide(u, ax: str, ay: str, gx: int, gy: int, t: int):
    """T-deep halo exchange: returns the (bm+2t, bn+2t) extended block —
    ``exchange_halo_strips`` assembled in HBM, for the jnp golden path
    (the Pallas hybrid kernels take the strips directly)."""
    north, south, west, east = exchange_halo_strips(u, ax, ay, gx, gy, t)
    vert = jnp.concatenate([north, u, south], axis=0)
    return jnp.concatenate([west, vert, east], axis=1)
