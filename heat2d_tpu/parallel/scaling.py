"""Strong-scaling measurement — the MULTICHIP gate's scaling metric.

BENCH tracks the single-chip trajectory; MULTICHIP runs previously only
proved the mesh program compiles and steps. This module adds the number
that actually tracks pod-scale progress (ISSUE 8 / ROADMAP item 2): the
same FIXED global problem measured on 1 device and on the full mesh,

    strong_scaling_efficiency = rate_n / (n * rate_1)

— 1.0 is perfect scaling; what the collective halo barrier eats at chunk
boundaries (and what the fused route exists to win back) shows up as the
gap. Records ride the unified run-record schema (kind="multichip") so
the scaling trajectory is tracked like BENCH_r*.json, and the driver's
MULTICHIP_r*.json captures the printed ``MULTICHIP_METRICS:`` line in
its ``tail``.
"""

from __future__ import annotations



def square_mesh(n: int) -> tuple[int, int]:
    """Closest-to-square (gx, gy) factorization of ``n`` — the mesh
    shape the reference hardcodes as GRIDX x GRIDY."""
    gx = int(n ** 0.5)
    while n % gx:
        gx -= 1
    return gx, n // gx


def _rate(cfg, devices) -> float:
    """Mcells/s of one sharded run under the reference timing protocol
    (compile excluded — utils.timing.timed_call inside Solver.run)."""
    from heat2d_tpu.models.solver import Heat2DSolver

    r = Heat2DSolver(cfg, devices=devices).run(gather=False)
    return r.mcells_per_s


def measure_strong_scaling(n_devices: int | None = None,
                           nx: int = 64, ny: int = 64, steps: int = 32,
                           halo: str = "collective", halo_depth=None,
                           mode: str = "dist2d", devices=None) -> dict:
    """One strong-scaling measurement: the FIXED (nx, ny) global grid
    advanced ``steps`` steps on 1 device and on an ``n_devices``
    near-square mesh, same mode and halo route. Returns the
    kind="multichip" record payload (per-chip Mcells/s at both points,
    the efficiency ratio, and the resolved halo route/tier so a fused
    request that degraded is visible in the record, not silent)."""
    import jax

    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.parallel.mesh import make_mesh
    from heat2d_tpu.parallel.sharded import resolve_halo_route

    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if len(devices) < n:
        raise ValueError(f"strong scaling at n={n} needs {n} devices; "
                         f"have {len(devices)}")
    gx, gy = square_mesh(n)
    base = dict(nxprob=nx, nyprob=ny, steps=steps, mode=mode,
                halo_depth=halo_depth)
    # The 1-chip baseline is the SAME program for every route
    # (collective — on one device there is no exchange to overlap, only
    # the fused route's seam-recompute tax): a route-specific baseline
    # would let a route inflate its efficiency ratio by being slower at
    # n=1, making cross-route efficiency comparisons (the acceptance
    # gate: fused no worse than collective) meaningless.
    cfg1 = HeatConfig(gridx=1, gridy=1, halo="collective", **base)
    cfgn = HeatConfig(gridx=gx, gridy=gy, halo=halo, **base)
    ck = None
    if mode == "hybrid":
        # The route resolves differently with a shard chunk kernel
        # (window / kernel-F tiers) — resolve against the SAME kernel
        # the solver will build, or the recorded tier describes a
        # program that never runs.
        from heat2d_tpu.ops.pallas_stencil import make_shard_chunk_kernel
        ck = make_shard_chunk_kernel(cfgn)
    route = resolve_halo_route(cfgn, make_mesh(gx, gy,
                                               devices=devices[:n]),
                               chunk_kernel=ck)
    rate_1 = _rate(cfg1, devices[:1])
    rate_n = _rate(cfgn, devices[:n])
    eff = (rate_n / (n * rate_1)) if rate_1 > 0 else float("nan")
    return {
        "n_devices": n, "mesh": [gx, gy], "grid": [nx, ny],
        "steps": steps, "mode": mode,
        "halo": halo, "halo_route": route["route"],
        "halo_tier": route["tier"], "halo_depth": route["depth"],
        "mcells_per_s_1chip": rate_1,
        "mcells_per_s_nchip": rate_n,
        "per_chip_mcells_per_s_1chip": rate_1,
        "per_chip_mcells_per_s_nchip": rate_n / n,
        "strong_scaling_efficiency": eff,
    }


def scaling_record(payloads: list, out_path: str | None = None) -> dict:
    """Wrap per-route scaling payloads in the unified run-record
    envelope (kind="multichip") and optionally write it as JSON —
    the MULTICHIP_r*.json companion the trajectory is tracked by."""
    from heat2d_tpu.obs.record import build_record

    rec = build_record("multichip", extra={"scaling": payloads})
    if out_path:
        from heat2d_tpu.io.binary import write_json_atomic
        write_json_atomic(rec, out_path, sort_keys=True)
    return rec
