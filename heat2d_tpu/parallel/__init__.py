from heat2d_tpu.parallel.mesh import make_mesh, mesh_devices_summary
from heat2d_tpu.parallel.halo import (
    exchange_halo_2d_wide,
    shift_from_lower,
    shift_from_upper,
)
from heat2d_tpu.parallel.sharded import (
    make_local_step,
    make_sharded_runner,
    sharded_inidat,
)

__all__ = [
    "make_mesh",
    "mesh_devices_summary",
    "shift_from_lower",
    "shift_from_upper",
    "exchange_halo_2d_wide",
    "make_local_step",
    "make_sharded_runner",
    "sharded_inidat",
]
