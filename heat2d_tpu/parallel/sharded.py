"""SPMD sharded solver path — shard_map + ppermute over a device mesh.

This is the TPU-native replacement for the reference's two distributed
programs (SURVEY.md §2.1 C7-C14):

- dist1d — 1D row-strip decomposition, the mpi_heat2Dn.c scheme, as a
  (numworkers, 1) mesh: only N/S halo traffic, no idle master (the
  reference's master rank never computes; here every device computes —
  the same fix the reference's own redesign made, Report.pdf p.16).
- dist2d — 2D block decomposition, the grad1612_mpi_heat.c scheme, as a
  (GRIDX, GRIDY) mesh with 4-neighbor ppermute halo exchange.

Everything runs inside one ``shard_map``-ed, jit-compiled function: the
whole time loop, the halo exchanges, and the convergence psum — the step
program is compiled once (the persistent-request analogue) and the grid
never leaves the devices until I/O.
"""

from __future__ import annotations

import types

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from heat2d_tpu.models import engine
from heat2d_tpu.ops.init import inidat_block
from heat2d_tpu.ops.stencil import residual_sq, stencil_step_padded
from heat2d_tpu.parallel.halo import (exchange_halo_2d_wide,
                                      exchange_halo_strips,
                                      fused_halo_viable)
from heat2d_tpu.parallel.mesh import shard_map_compat
from heat2d_tpu.utils.profiling import phase

#: Default wide-halo depth (config.halo_depth=None): 8 steps per exchange,
#: clamped to the shard size in make_local_chunk.
DEFAULT_HALO_DEPTH = 8

#: The DECLARED communication contract of every sharded halo route —
#: what the IR verifier's collective pass (analysis/ir.py) checks each
#: traced shard_map program against. The halo exchange is exactly 4
#: ppermutes per chunk (2 N/S strip shifts + 2 E/W shifts of the
#: vertically-extended edge columns — parallel/halo.py), every
#: permutation is a nearest-neighbor non-wrapping pair, psum appears
#: only for the convergence residual, and the gather-family
#: collectives are categorically forbidden: an accidental all_gather
#: turns O(halo) bytes into O(grid) bytes per step — the classic
#: silent 100x regression this contract exists to catch.
#: ``pbroadcast`` is modern shard_map's replication *annotation* (vma
#: bookkeeping), not a data transfer.
COLLECTIVE_CONTRACT = {
    "allowed": ("ppermute", "psum", "pbroadcast"),
    "forbidden": ("all_gather", "all_to_all", "reduce_scatter",
                  "pgather", "psum_scatter"),
    #: ppermutes per halo exchange; every traced exchange site must
    #: carry a positive multiple of this.
    "ppermutes_per_exchange": 4,
    #: |src - dst| for every permutation pair (non-wrapping
    #: nearest-neighbor shifts; edge shards receive zeros).
    "neighbor_distance": 1,
}


def _mesh_axes(mesh: Mesh, axes=None) -> tuple[str, str, int, int]:
    """(ax, ay, gx, gy) of the SPATIAL mesh axes. For the plain 2-axis
    meshes of dist1d/dist2d/hybrid these are the mesh itself; a 3-axis
    batchxspatial ensemble mesh ('b','x','y') passes its spatial axes
    explicitly — every helper below shards space over exactly these two
    axes and never sees the batch axis."""
    if axes is not None:
        return axes
    ax, ay = mesh.axis_names
    return ax, ay, mesh.devices.shape[0], mesh.devices.shape[1]


def padded_global_shape(config, mesh: Mesh, axes=None) -> tuple[int, int]:
    """Global shape padded up so every shard is equal-sized — the TPU
    answer to the reference's uneven averow/extra strips
    (mpi_heat2Dn.c:89-94): instead of first-k-shards-get-one-extra-row,
    pad to the next multiple and let the out-of-domain rows sit inert
    (they are outside the keep-mask's interior, never update, stay 0, and
    contribute 0 to the convergence residual)."""
    _, _, gx, gy = _mesh_axes(mesh, axes)
    pnx = -(-config.nxprob // gx) * gx
    pny = -(-config.nyprob // gy) * gy
    return pnx, pny


def _keep_mask(shape, nx, ny, row0, col0):
    """Boolean ``shape`` mask: True where the cell must be KEPT (never
    updated) — global-boundary cells (the reference's loop bounds / CUDA
    guard grad1612_cuda_heat.cu:58) and out-of-domain halo cells (gi<0 /
    gi>nx-1), which stay at their ghost value so edge zeros are firewalled
    at the boundary. ``row0``/``col0``: global indices of element (0, 0).
    (Row-only variant lives in ops/pallas_stencil._band_multi_kernel,
    whose bands span the full grid width.)"""
    gi = row0 + lax.broadcasted_iota(jnp.int32, shape, 0)
    gj = col0 + lax.broadcasted_iota(jnp.int32, shape, 1)
    return (gi <= 0) | (gi >= nx - 1) | (gj <= 0) | (gj >= ny - 1)


def make_local_step(config, mesh: Mesh, chunk_kernel=None, axes=None,
                    cxy=None):
    """Shard-local single step — the wide-halo chunk at depth 1 (bitwise
    identical per the depth-parametrized tests; used as the tracked step
    of the convergence residual pair).

    ``chunk_kernel``: optional Pallas chunk implementation (see
    make_local_chunk) replacing the jnp golden loop.
    """
    chunk = make_local_chunk(config, mesh, chunk_kernel=chunk_kernel,
                             axes=axes, cxy=cxy)
    return lambda u: chunk(u, 1)


def make_local_chunk(config, mesh: Mesh, chunk_kernel=None, axes=None,
                     cxy=None):
    """Shard-local multi-step: ONE wide halo exchange, then T steps in
    place on the (bm+2T, bn+2T) extended block.

    Halo-depth correctness mirrors the Pallas temporal blocking
    (ops/pallas_stencil.py): after s local steps the outermost s cells of
    the extended block are stale; the kept center sits T cells in, and the
    global clamp mask is applied every internal step so out-of-domain
    ghost zeros at physical edges are firewalled at the boundary cells
    (which never update). Returns ``chunk(u, t)`` with static t in
    [1, min(bm, bn)].

    ``chunk_kernel``: optional ``(u, strips, t, x0, y0) -> u_new``
    advancing the shard block t steps in one Pallas invocation (mode=
    'hybrid', ops.pallas_stencil.make_shard_chunk_kernel) — it takes the
    four halo strips directly and assembles the extended block in VMEM,
    so only strip-sized arrays ever move through HBM around the kernel
    (the round-2 path paid three full-block HBM round-trips per chunk).
    VMEM-routed so arbitrarily large shards stream in row bands instead
    of OOMing.

    ``cxy``: optional (cx, cy) overriding the config's diffusivities —
    may be TRACED values (the batchxspatial ensemble builds the chunk
    inside a vmap with per-member scalars); chunk_kernel, which bakes
    its constants, cannot be combined with it.
    """
    ax, ay, gx, gy = _mesh_axes(mesh, axes)
    nx, ny = config.nxprob, config.nyprob   # true domain (masks use these)
    pnx, pny = padded_global_shape(config, mesh, axes)
    bm, bn = pnx // gx, pny // gy
    accum = jnp.dtype(config.accum_dtype)
    cx, cy = cxy if cxy is not None else (config.cx, config.cy)
    if cxy is not None and chunk_kernel is not None:
        raise ValueError("per-member cxy requires the jnp chunk path "
                         "(chunk kernels bake their diffusivities)")
    fused_req = getattr(config, "halo", "collective") == "fused"
    fused_ici = None
    if fused_req and chunk_kernel is not None:
        from heat2d_tpu.ops import pallas_stencil as ps
        fused_ici = ps.make_fused_chunk_kernel(config, (ax, ay, gx, gy))

    def advance(v, row0, col0, t):
        """t masked steps on a sub-block whose (0,0) sits at global
        (row0, col0) — the ONE per-cell step expression both halo
        routes share, so every kept cell's arithmetic DAG is identical
        between them (the bitwise-parity contract)."""
        keep = _keep_mask(v.shape, nx, ny, row0, col0)

        def one(_, w):
            newint = stencil_step_padded(w, cx, cy, accum)
            mid = jnp.concatenate([w[1:-1, :1], newint, w[1:-1, -1:]],
                                  axis=1)
            full = jnp.concatenate([w[:1, :], mid, w[-1:, :]], axis=0)
            return jnp.where(keep, w, full)

        return lax.fori_loop(0, t, one, v, unroll=False)

    def chunk_fused(u, t, x0, y0):
        """Overlap schedule (config.halo='fused', jnp path): the
        reference's inner/boundary split (grad1612_mpi_heat.c:233-259)
        — the interior sweep is traced with NO data dependency on the
        exchanged strips, so XLA runs the 4 ppermutes while the
        interior advances; the four t-wide boundary frames are then
        recomputed from strip-extended regions and stitched in. Every
        kept cell's per-step arithmetic is the chunk() expression on
        the same operand values (the temporal-blocking cone argument,
        kernel C), so the result is BITWISE equal to the collective
        route — at ~(6t(bm + bn)/(bm*bn)) recompute overhead per step,
        the same seam tax the reference paid for its overlap."""
        with phase("halo_overlap"):
            north, south, west, east = exchange_halo_strips(
                u, ax, ay, gx, gy, t)
        with phase("interior_stencil"):
            # Exact after t steps at distance >= t from the block edge.
            core = advance(u, x0, y0, t)[t:bm - t, t:bn - t]
        with phase("halo_overlap"):
            # N/S frames: rows [0,t) / [bm-t,bm), interior cols only —
            # their corner cols ride in the full-height W/E frames.
            nfr = advance(jnp.concatenate([north, u[:2 * t]], axis=0),
                          x0 - t, y0, t)[t:2 * t, t:bn - t]
            sfr = advance(jnp.concatenate([u[bm - 2 * t:], south], axis=0),
                          x0 + bm - 2 * t, y0, t)[t:2 * t, t:bn - t]
            # W/E frames: all rows, cols [0,t) / [bn-t,bn) — assembled
            # from the vertically-extended edge columns (the exchanged
            # strips carry the corners, exchange_halo_strips).
            vert = jnp.concatenate([north, u, south], axis=0)
            wfr = advance(jnp.concatenate([west, vert[:, :2 * t]], axis=1),
                          x0 - t, y0 - t, t)[t:bm + t, t:2 * t]
            efr = advance(jnp.concatenate([vert[:, bn - 2 * t:], east],
                                          axis=1),
                          x0 - t, y0 + bn - 2 * t, t)[t:bm + t, t:2 * t]
            mid = jnp.concatenate([nfr, core, sfr], axis=0)
            return jnp.concatenate([wfr, mid, efr], axis=1)

    def chunk(u, t):
        # phase() spans: metadata-only HLO scope names so XProf/Perfetto
        # (and heat2d-tpu-prof) attribute ops to halo-exchange vs
        # interior-stencil — the per-callsite flavor of the mpiP tables.
        x0 = lax.axis_index(ax) * bm
        y0 = lax.axis_index(ay) * bn
        if chunk_kernel is not None:
            if fused_ici is not None and fused_ici.viable(t):
                # Kernel F: the exchange itself moves into the Pallas
                # kernel as async remote copies over ICI.
                with phase("stencil_chunk"):
                    return fused_ici(u, t, lax.axis_index(ax),
                                     lax.axis_index(ay), x0, y0)
            with phase("halo_exchange"):
                strips = exchange_halo_strips(u, ax, ay, gx, gy, t)
            with phase("stencil_chunk"):
                return chunk_kernel(u, strips, t, x0, y0)
        # gx*gy == 1: no neighbors, nothing to overlap — the seam
        # recompute would be pure waste (and a route-dependent 1-chip
        # baseline would skew the strong-scaling gate).
        if fused_req and gx * gy > 1 and fused_halo_viable(bm, bn, t):
            return chunk_fused(u, t, x0, y0)
        with phase("halo_exchange"):
            ext = exchange_halo_2d_wide(u, ax, ay, gx, gy, t)

        with phase("interior_stencil"):
            ext = advance(ext, x0 - t, y0 - t, t)
        return ext[t:-t, t:-t]

    return chunk


def _tuned_fused_depth(bm: int, bn: int, config):
    """Tuned overlap depth for the fused halo route from the opt-in
    tuning db (``HEAT2D_TUNE_DB``), or None — consulted only when the
    fused route is REQUESTED and no explicit --halo-depth pins the
    depth, so collective-route programs (and db-less builds) stay
    byte-identical (the jaxpr-pinned contract). The answer is
    re-validated by tune.runtime.fused_config against the live overlap
    geometry + VMEM model before it may steer the schedule."""
    try:
        from heat2d_tpu.tune import runtime as _tune_runtime
    except ImportError:  # pragma: no cover - partial install
        return None
    cfg = _tune_runtime.fused_config(bm, bn, "float32")
    return cfg.tsteps if cfg is not None else None


def effective_halo_depth(config, mesh: Mesh, axes=None) -> int:
    _, _, gx, gy = _mesh_axes(mesh, axes)
    pnx, pny = padded_global_shape(config, mesh, axes)
    bm, bn = pnx // gx, pny // gy
    want = config.halo_depth or DEFAULT_HALO_DEPTH
    if (config.halo_depth is None
            and getattr(config, "halo", "collective") == "fused"):
        tuned = _tuned_fused_depth(bm, bn, config)
        if tuned:
            want = tuned
    return max(1, min(want, bm, bn))


def resolve_halo_route(config, mesh: Mesh, chunk_kernel=None,
                       axes=None) -> dict:
    """Host-side description of the halo route a runner build will
    actually take at the full chunk depth — the provenance block run
    records/launch logs carry, and what the parity tests assert
    degradation against. ``tier``:

    - ``"collective"`` — the existing exchange-then-compute schedule
      (also what a non-viable fused request degrades to);
    - ``"overlap"``    — fused via the explicit inner/boundary split
      (ppermute strips overlapped with the interior sweep);
    - ``"ici"``        — fused via in-kernel async remote copies
      (kernel F; TPU + resident shard only);
    - ``"window"``     — the D2 gather-free sweep route (hybrid,
      band-streamed shards) — its per-sweep exchange stays collective;
      a fused request records the degradation here.
    """
    ax, ay, gx, gy = _mesh_axes(mesh, axes)
    pnx, pny = padded_global_shape(config, mesh, axes)
    bm, bn = pnx // gx, pny // gy
    t = effective_halo_depth(config, mesh, axes)
    requested = getattr(config, "halo", "collective")
    out = dict(requested=requested, depth=t, shard=(bm, bn),
               mesh=(gx, gy))
    if requested != "fused":
        out.update(route="collective", tier="collective")
        return out
    if chunk_kernel is not None:
        window = make_window_multi(config, mesh)
        if window is not None:
            out.update(route="collective", tier="window")
            return out
        from heat2d_tpu.ops import pallas_stencil as ps
        fused_ici = ps.make_fused_chunk_kernel(config, (ax, ay, gx, gy))
        if fused_ici is not None and fused_ici.viable(t):
            out.update(route="fused", tier="ici")
            return out
        out.update(route="collective", tier="collective")
        return out
    if gx * gy > 1 and fused_halo_viable(bm, bn, t):
        out.update(route="fused", tier="overlap")
        return out
    out.update(route="collective", tier="collective")
    return out


def make_local_multi(config, mesh: Mesh, chunk_kernel=None, axes=None,
                     cxy=None):
    """``multi(u, n)`` advancing a *static* n steps via wide-halo chunks
    of depth T plus a remainder chunk."""
    chunk = make_local_chunk(config, mesh, chunk_kernel=chunk_kernel,
                             axes=axes, cxy=cxy)
    t = effective_halo_depth(config, mesh, axes)

    def multi(u, n):
        full, rem = divmod(n, t)
        if full:
            u = lax.fori_loop(0, full, lambda _, v: chunk(v, t), u,
                              unroll=False)
        if rem:
            u = chunk(u, rem)
        return u

    return multi


def make_window_multi(config, mesh: Mesh):
    """Gather-free hybrid sweeps (Pallas kernel D2) over an EXTENDED
    (m_pad + T, bn) shard carry: rows [0, bm) the block, [bm, bm+T) the
    current sweep's south halo — refreshed in place per sweep (a
    strip-sized dynamic_update_slice) instead of re-assembling strip
    operands per chunk, the same per-sweep copy elimination kernel C2
    made for the single-chip path — and [bm+T, m_pad+T) inert pad for
    divisor-poor shard heights (m_pad == bm when rb divides bm; see
    plan_shard_window for the pad-correctness argument). Returns None
    when the route is not viable (off-TPU, parity mode, resident-size
    shards, misaligned shapes) — kernel D keeps those; else a namespace
    of closures (``multi``, ``step``, ``extend``, ``strip``,
    ``chunk_resid`` for the fused D2R convergence path, and the sweep
    ``depth``) for make_sharded_runner, all operating on the extended
    carry and only callable inside shard_map."""
    from heat2d_tpu.ops import pallas_stencil as ps
    if getattr(config, "bitwise_parity", False):
        return None     # the FMA-form-only route (the C2 envelope gate)
    ax, ay = mesh.axis_names
    gx, gy = (mesh.devices.shape[0], mesh.devices.shape[1])
    pnx, pny = padded_global_shape(config, mesh)
    bm, bn = pnx // gx, pny // gy
    t = effective_halo_depth(config, mesh)
    if ps.fits_vmem((bm + 2 * t, bn + 2 * t)):
        return None     # whole-block-resident kernel D is already fused
    with_cols = gy > 1
    plan = ps.plan_shard_window(bm, bn, t, with_cols=with_cols)
    if plan is None:
        return None
    rb, m_pad = plan
    nblk = m_pad // rb
    pad_rows = m_pad - bm
    cx, cy = config.cx, config.cy
    nx, ny = config.nxprob, config.nyprob

    def sweep(ue, nsub=None, resid=False):
        core = ue[:bm]
        with phase("halo_exchange"):
            north, south, west, east = exchange_halo_strips(
                core, ax, ay, gx, gy, t)
        ue = lax.dynamic_update_slice(ue, south, (bm, 0))
        if with_cols:
            if pad_rows:
                # Column strips must cover the pad bands' windows too
                # (strip rows [bm+T, m_pad+T) sit in the garbage zone —
                # values there only ever feed pad-row updates).
                zpad = jnp.zeros((pad_rows, t), ue.dtype)
                west_p = jnp.concatenate([west, zpad], axis=0)
                east_p = jnp.concatenate([east, zpad], axis=0)
            else:
                west_p, east_p = west, east
            wwin = ps._strip_windows(west_p, nblk, rb, t)
            ewin = ps._strip_windows(east_p, nblk, rb, t)
        else:
            wwin = ewin = None
        scalars = jnp.stack(
            [(lax.axis_index(ax) * bm).astype(jnp.int32),
             (lax.axis_index(ay) * bn).astype(jnp.int32)])
        with phase("stencil_chunk"):
            return ps.shard_window_sweep(ue, north, wwin, ewin, scalars,
                                         rb=rb, tsteps=t, nx=nx, ny=ny,
                                         cx=cx, cy=cy, nsub=nsub,
                                         resid=resid, valid_rows=bm)

    def multi(ue, n):
        full, rem = divmod(n, t)
        if full:
            ue = lax.fori_loop(0, full, lambda _, v: sweep(v), ue,
                               unroll=False)
        if rem:
            # Chunk remainders (and the unfused tracked step) stay on
            # the window route as partial-depth sweeps.
            ue = sweep(ue, nsub=rem)
        return ue

    def chunk_resid(ue, n):
        """``n >= 1`` steps + this chunk's GLOBAL residual: the last
        sweep is a D2R sweep whose per-shard partial psums across the
        mesh (the MPI_Allreduce, fused into the kernel's tail). The
        resid sweep advances only the chunk-tail depth (n % t, or a
        full t when t | n) so every other sweep is a full fast-path
        sweep — round 5: hybrid conv overhead 14.8% -> see
        sweep_conv.md. For n < t the whole chunk IS the resid sweep
        (multi runs zero sweeps) — the small-interval path tpu_smoke
        pins."""
        d = n % t or t
        ue = multi(ue, n - d)
        ue, part = sweep(ue, nsub=d, resid=True)
        with phase("residual_reduction"):
            return ue, lax.psum(part, (ax, ay))

    def extend(u):
        return jnp.concatenate(
            [u, jnp.zeros((pad_rows + t, bn), u.dtype)], axis=0)

    return types.SimpleNamespace(
        multi=multi, step=(lambda ue: multi(ue, 1)), extend=extend,
        strip=(lambda ue: ue[:bm]), chunk_resid=chunk_resid, depth=t)


def make_sharded_runner(config, mesh: Mesh, chunk_kernel=None, tap=None):
    """Returns (runner, sharding): ``runner(u_sharded) -> (u, steps_done)``,
    jit-compiled over the mesh. The full loop (and convergence psum over
    both mesh axes — the MPI_Allreduce analogue, grad1612_mpi_heat.c:268)
    runs device-side in one program.

    ``tap``: optional in-loop residual stream (engine._emit). Inside
    shard_map the callback fires once per shard with the replicated
    psum'd residual — TelemetryStream dedupes by step. None keeps the
    traced program identical to the untelemetered one."""
    ax, ay = mesh.axis_names
    accum = jnp.dtype(config.accum_dtype)
    local_step = make_local_step(config, mesh, chunk_kernel=chunk_kernel)
    local_multi = make_local_multi(config, mesh, chunk_kernel=chunk_kernel)
    # chunk_kernel's presence is the mode='hybrid' signal; the window
    # route itself no longer needs the kernel-D chunk builder (its
    # remainders are partial-depth window sweeps).
    window = (make_window_multi(config, mesh)
              if chunk_kernel is not None else None)
    sharding = NamedSharding(mesh, P(ax, ay))

    def local_run(u):
        if window is not None:
            ue = window.extend(u)
            if config.convergence:
                if accum == jnp.float32:
                    # (accum gate: the D2R kernel sums its partials in
                    # f32; a float64-accum residual must stay on the
                    # unfused path below, which honors it. Any
                    # interval >= 1 is viable since the round-5
                    # chunk-tail resid schedule.)
                    # Fused D2R path: tracked step + residual + psum
                    # fold into the chunk's last sweep.
                    ue, k = engine.run_convergence_fused(
                        window.chunk_resid, window.multi, ue,
                        config.steps, config.interval,
                        config.sensitivity, tap=tap)
                else:
                    def residual_w(u_new, u_old):
                        with phase("residual_reduction"):
                            return lax.psum(
                                residual_sq(window.strip(u_new),
                                            window.strip(u_old), accum),
                                (ax, ay))
                    ue, k = engine.run_convergence_chunked(
                        window.multi, window.step, residual_w, ue,
                        config.steps, config.interval,
                        config.sensitivity, tap=tap)
            else:
                ue = window.multi(ue, config.steps)
                k = jnp.asarray(config.steps, jnp.int32)
            return window.strip(ue), k
        if config.convergence:
            def residual(u_new, u_old):
                with phase("residual_reduction"):
                    return lax.psum(residual_sq(u_new, u_old, accum),
                                    (ax, ay))
            u, k = engine.run_convergence_chunked(
                local_multi, local_step, residual, u, config.steps,
                config.interval, config.sensitivity, tap=tap)
        else:
            u = local_multi(u, config.steps)
            k = jnp.asarray(config.steps, jnp.int32)
        return u, k

    # check_vma off in hybrid mode (pallas_call out_shapes carry no
    # varying-across-mesh-axes info), when a telemetry tap is wired in
    # (debug_callback has no replication rule, which poisons the whole
    # while loop's check), and on convergence runs under LEGACY jax
    # only (experimental shard_map's check_rep has no replication rule
    # for while; the top-level jax.shard_map vma check handles it, so
    # modern jax keeps the check and still catches un-psum'd leaks).
    legacy_rep_check = not hasattr(jax, "shard_map")
    mapped = shard_map_compat(local_run, mesh,
                              in_specs=P(ax, ay),
                              out_specs=(P(ax, ay), P()),
                              check_vma=(chunk_kernel is None
                                         and tap is None
                                         and not (config.convergence
                                                  and legacy_rep_check)))
    runner = jax.jit(mapped)
    return runner, sharding


def sharded_inidat(config, mesh: Mesh):
    """Device-resident sharded initial condition. Each shard computes its
    block from its mesh coordinates (lax.axis_index) — no xs/ys offset
    broadcast needed (grad1612_mpi_heat.c:125-147 collapses to this)."""
    ax, ay = mesh.axis_names
    gx, gy = (mesh.devices.shape[0], mesh.devices.shape[1])
    nx, ny = config.nxprob, config.nyprob
    pnx, pny = padded_global_shape(config, mesh)
    bm, bn = pnx // gx, pny // gy

    def local_init():
        x0 = lax.axis_index(ax) * bm
        y0 = lax.axis_index(ay) * bn
        val = inidat_block((bm, bn), nx, ny, x0, y0)
        # Out-of-domain pad cells (uneven shards) hold 0 forever.
        gi = x0 + lax.broadcasted_iota(jnp.int32, (bm, bn), 0)
        gj = y0 + lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
        return jnp.where((gi < nx) & (gj < ny), val, 0.0)

    fn = jax.jit(shard_map_compat(local_init, mesh, in_specs=(),
                                  out_specs=P(ax, ay)))
    return fn()
