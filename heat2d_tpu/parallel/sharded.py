"""SPMD sharded solver path — shard_map + ppermute over a device mesh.

This is the TPU-native replacement for the reference's two distributed
programs (SURVEY.md §2.1 C7-C14):

- dist1d — 1D row-strip decomposition, the mpi_heat2Dn.c scheme, as a
  (numworkers, 1) mesh: only N/S halo traffic, no idle master (the
  reference's master rank never computes; here every device computes —
  the same fix the reference's own redesign made, Report.pdf p.16).
- dist2d — 2D block decomposition, the grad1612_mpi_heat.c scheme, as a
  (GRIDX, GRIDY) mesh with 4-neighbor ppermute halo exchange.

Everything runs inside one ``shard_map``-ed, jit-compiled function: the
whole time loop, the halo exchanges, and the convergence psum — the step
program is compiled once (the persistent-request analogue) and the grid
never leaves the devices until I/O.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from heat2d_tpu.models import engine
from heat2d_tpu.ops.init import inidat_block
from heat2d_tpu.ops.stencil import residual_sq, stencil_step_padded
from heat2d_tpu.parallel.halo import exchange_halo_2d, pad_with_halo


def _interior_mask(bm, bn, nx, ny, ax, ay):
    """Boolean (bm, bn): True where this shard's cell is a *global* interior
    cell (the only cells the reference ever updates — its loop bounds and
    the CUDA guard grad1612_cuda_heat.cu:58)."""
    row0 = lax.axis_index(ax) * bm
    col0 = lax.axis_index(ay) * bn
    gi = lax.broadcasted_iota(jnp.int32, (bm, bn), 0) + row0
    gj = lax.broadcasted_iota(jnp.int32, (bm, bn), 1) + col0
    return ((gi >= 1) & (gi <= nx - 2)) & ((gj >= 1) & (gj <= ny - 2))


def make_local_step(config, mesh: Mesh, kernel=None):
    """Shard-local step: halo exchange -> stencil -> global-boundary mask.

    ``kernel``: optional (padded, cx, cy) -> (bm, bn) stencil implementation
    (e.g. the Pallas kernel) replacing the jnp golden model.
    """
    ax, ay = mesh.axis_names
    gx, gy = (mesh.devices.shape[0], mesh.devices.shape[1])
    nx, ny = config.nxprob, config.nyprob
    bm, bn = nx // gx, ny // gy
    accum = jnp.dtype(config.accum_dtype)
    cx, cy = config.cx, config.cy

    def local_step(u):
        halos = exchange_halo_2d(u, ax, ay, gx, gy)
        padded = pad_with_halo(u, *halos)
        if kernel is None:
            new = stencil_step_padded(padded, cx, cy, accum)
        else:
            new = kernel(padded, cx, cy)
        mask = _interior_mask(bm, bn, nx, ny, ax, ay)
        return jnp.where(mask, new, u)

    return local_step


def make_sharded_runner(config, mesh: Mesh, kernel=None):
    """Returns (runner, sharding): ``runner(u_sharded) -> (u, steps_done)``,
    jit-compiled over the mesh. The full loop (and convergence psum over
    both mesh axes — the MPI_Allreduce analogue, grad1612_mpi_heat.c:268)
    runs device-side in one program."""
    ax, ay = mesh.axis_names
    accum = jnp.dtype(config.accum_dtype)
    local_step = make_local_step(config, mesh, kernel=kernel)
    sharding = NamedSharding(mesh, P(ax, ay))

    def local_run(u):
        if config.convergence:
            def residual(u_new, u_old):
                return lax.psum(residual_sq(u_new, u_old, accum),
                                (ax, ay))
            u, k = engine.run_convergence(
                local_step, residual, u, config.steps,
                config.interval, config.sensitivity)
        else:
            u, k = engine.run_fixed(local_step, u, config.steps)
        return u, k

    try:
        mapped = shard_map(local_run, mesh=mesh,
                           in_specs=P(ax, ay),
                           out_specs=(P(ax, ay), P()),
                           # pallas_call out_shapes carry no vma info; skip
                           # the varying-across-mesh-axes check when a
                           # kernel runs inside the shard (hybrid mode)
                           check_vma=kernel is None)
    except TypeError:  # older jax: no check_vma kwarg
        mapped = shard_map(local_run, mesh=mesh,
                           in_specs=P(ax, ay),
                           out_specs=(P(ax, ay), P()))
    runner = jax.jit(mapped)
    return runner, sharding


def sharded_inidat(config, mesh: Mesh):
    """Device-resident sharded initial condition. Each shard computes its
    block from its mesh coordinates (lax.axis_index) — no xs/ys offset
    broadcast needed (grad1612_mpi_heat.c:125-147 collapses to this)."""
    ax, ay = mesh.axis_names
    gx, gy = (mesh.devices.shape[0], mesh.devices.shape[1])
    nx, ny = config.nxprob, config.nyprob
    bm, bn = nx // gx, ny // gy

    def local_init():
        x0 = lax.axis_index(ax) * bm
        y0 = lax.axis_index(ay) * bn
        return inidat_block((bm, bn), nx, ny, x0, y0)

    fn = jax.jit(shard_map(local_init, mesh=mesh, in_specs=(),
                           out_specs=P(ax, ay)))
    return fn()
