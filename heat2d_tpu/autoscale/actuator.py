"""The actuator: control-plane advice in, fleet/mesh actions out.

``Actuator.observe`` consumes one ``load/capacity.advise`` sizing row
per control tick and converges the worker pool toward it through the
``AutoscalePolicy`` guardrails — never above ``max_workers``, never
below ``min_workers``, scale-ups rate-limited by a cooldown, scale-downs
additionally gated behind ``down_hold_ticks`` consecutive quiet
observations (one quiet window is noise, N in a row is a trough).

Actions are executed, not just recommended:

- **scale-up** — ``FleetServer.add_worker``: the new worker rides the
  warm-restart machinery (spawned ``via="scale_up"``, cold-gated by
  the router until its hot signatures are compiled — it is UNROUTABLE
  until then, so a scale-up can never serve a cold compile to a
  client).
- **scale-down** — ``Actuator.retire``: any long-running inverse jobs
  attached to the victim are live-migrated first (pause → wire ticket
  → resume on the lowest-numbered survivor), then
  ``FleetServer.retire_worker`` runs the fence-then-drain protocol
  (router fenced BEFORE the shutdown line, in-flight work flushed by
  pipe FIFO order or replayed on an unclean drain).
- **parole** — quarantined mesh devices get a hearing
  (``HealthMonitor.parole``): N consecutive verified probe passes
  re-admit the device under a seq-fenced event, so
  ``no_quarantined_serving`` stays provable across the re-admission.
- **mesh resize** — voluntary ``MeshEnsembleEngine.resize`` in either
  direction.

The actuator also keeps the chip-seconds ledger: the integral of
pool size over wall time, compared in ``summary()`` against the static
baseline (``max_workers`` for the whole window) that a non-elastic
deployment would have paid. That ratio is the CI gate's
"cheaper than static provisioning" verdict.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Dict, List, Optional

from heat2d_tpu.autoscale.policy import AutoscalePolicy
from heat2d_tpu.autoscale import migrate as _migrate

log = logging.getLogger("heat2d.autoscale")


class Actuator:
    """Executes sizing advice against a live ``FleetServer`` (and,
    optionally, a mesh engine + health monitor). See module docstring.

    ``clock`` is injectable (tests drive cooldowns deterministically);
    production uses ``time.monotonic``."""

    def __init__(self, fleet, policy: Optional[AutoscalePolicy] = None,
                 *, registry=None, clock=None, mesh_engine=None,
                 health=None):
        import time as _time
        self.fleet = fleet
        self.policy = policy or AutoscalePolicy()
        self.registry = registry
        self.clock = clock or _time.monotonic
        self.mesh_engine = mesh_engine
        self.health = health
        self._lock = threading.Lock()
        #: audit trail of every action taken, in order
        self.actions: List[dict] = []
        #: one row per migrated inverse job
        self.migrations: List[dict] = []
        #: (t, pool_size) samples — one per observe(), for the
        #: capacity-vs-envelope plot/assert
        self.trace: List[tuple] = []
        self._jobs: Dict[int, List[object]] = {}
        self._below = 0                 # consecutive below-target ticks
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None
        # chip-seconds ledger: integral of pool_size dt since first
        # observation
        self._t0: Optional[float] = None
        self._last_t: Optional[float] = None
        self._chip_seconds = 0.0

    # -- ledger ---------------------------------------------------------- #

    def pool_size(self) -> int:
        return self.fleet.sup.pool_size()

    def _integrate(self, now: float) -> None:
        if self._t0 is None:
            self._t0 = now
        elif now > self._last_t:
            self._chip_seconds += (now - self._last_t) * self.pool_size()
        self._last_t = now
        if self.registry is not None:
            self.registry.gauge("autoscale_chip_seconds",
                                self._chip_seconds)

    def _record(self, action: str, **fields) -> dict:
        row = {"t": self._last_t, "action": action, **fields}
        self.actions.append(row)
        if self.registry is not None:
            self.registry.counter("autoscale_actions_total",
                                  action=action)
            self.registry.gauge("autoscale_workers",
                                float(self.pool_size()))
        return row

    # -- the control-tick entry point ------------------------------------ #

    def observe(self, advice: Optional[dict]) -> List[dict]:
        """Consume one sizing row (or ``None`` — still integrates the
        ledger) and take at most one action's worth of steps. Returns
        the action rows appended this tick."""
        pol = self.policy
        now = self.clock()
        with self._lock:
            self._integrate(now)
            cur = self.pool_size()
            self.trace.append((now, cur))
            if advice is None:
                return []
            target = int(advice.get("needed_units", cur))
            target = max(pol.min_workers, min(pol.max_workers, target))
            taken: List[dict] = []
            if target > cur:
                self._below = 0
                if (self._last_up is not None
                        and now - self._last_up < pol.up_cooldown_s):
                    return []
                k = min(target - cur, pol.max_step_up)
                slots = [self.fleet.add_worker() for _ in range(k)]
                self._last_up = now
                taken.append(self._record(
                    "scale_up", slots=slots, pool=self.pool_size(),
                    target=target))
                log.info("scale-up +%d -> %d (target %d)", k,
                         self.pool_size(), target)
            elif target < cur:
                self._below += 1
                if self._below < pol.down_hold_ticks:
                    return []
                if (self._last_down is not None
                        and now - self._last_down < pol.down_cooldown_s):
                    return []
                k = min(cur - target, pol.max_step_down)
                # victims: the highest-numbered provisioned slots —
                # the most recently added, so the steady-state pool
                # keeps its longest-warmed workers
                victims = self.fleet.sup.provisioned_slots()[-k:]
                self._last_down = now
                self._below = 0
                for slot in victims:
                    taken.append(self._retire_locked(slot, target))
            else:
                self._below = 0
            return taken

    # -- scale-down / migration ------------------------------------------ #

    def attach_job(self, slot: int, job) -> None:
        """Pin a long-running ``migrate.InverseJob`` to a worker slot:
        if that slot is ever retired, the job is live-migrated to a
        survivor first."""
        with self._lock:
            self._jobs.setdefault(int(slot), []).append(job)

    def jobs_on(self, slot: int) -> List[object]:
        with self._lock:
            return list(self._jobs.get(int(slot), ()))

    def retire(self, slot: int) -> dict:
        """Explicitly retire one worker (migrating its jobs). The
        scale-down path in ``observe`` funnels through the same code."""
        with self._lock:
            if self._last_t is None:
                self._integrate(self.clock())
            return self._retire_locked(slot, target=None)

    def _retire_locked(self, slot: int, target: Optional[int]) -> dict:
        migrated = self._migrate_jobs(slot)
        clean = self.fleet.retire_worker(
            slot, timeout=self.policy.drain_timeout_s)
        row = self._record("scale_down", slot=slot, clean=clean,
                           migrated=migrated, pool=self.pool_size(),
                           target=target)
        log.info("retired worker %d (clean=%s, migrated %d job(s))",
                 slot, clean, len(migrated))
        return row

    def _migrate_jobs(self, slot: int) -> List[dict]:
        """Checkpoint every job attached to ``slot``, ship each ticket
        through a JSON round trip (proving wire transportability), and
        resume on the lowest-numbered surviving slot. Caller holds the
        lock."""
        jobs = self._jobs.pop(int(slot), [])
        out: List[dict] = []
        for job in jobs:
            ticket = job.checkpoint()
            if ticket is None:
                # finished before the pause landed — nothing to move
                out.append({"from": slot, "to": None,
                            "iteration": job.completed_iterations(),
                            "resumed": False})
                continue
            wire_line = json.dumps(ticket)
            resumed = _migrate.resume_job(wire_line,
                                          registry=self.registry)
            survivors = [s for s in self.fleet.sup.provisioned_slots()
                         if s != slot]
            dest = survivors[0] if survivors else None
            if dest is not None:
                self._jobs.setdefault(dest, []).append(resumed)
            rec = {"from": slot, "to": dest,
                   "iteration": ticket["state"]["iteration"],
                   "bytes": len(wire_line), "resumed": True}
            out.append(rec)
            self.migrations.append(rec)
            if self.registry is not None:
                self.registry.counter("autoscale_migrations_total")
            log.info("migrated inverse job %d -> %s at iteration %d "
                     "(%d wire bytes)", slot, dest, rec["iteration"],
                     rec["bytes"])
        return out

    # -- mesh actions ---------------------------------------------------- #

    def parole_all(self, passes: Optional[int] = None) -> List[dict]:
        """Give every quarantined device a parole hearing. Re-admission
        requires ``passes`` consecutive verified probe passes; a single
        failure denies (the device stays quarantined, no event)."""
        if self.health is None:
            return []
        if self._last_t is None:
            self._integrate(self.clock())
        n = self.policy.parole_passes if passes is None else int(passes)
        rows: List[dict] = []
        for dev in sorted(self.health.quarantined()):
            ok = self.health.parole(dev, passes=n)
            rows.append(self._record(
                "parole", device=dev,
                outcome="paroled" if ok else "denied"))
        return rows

    def resize_mesh(self, n: int) -> Optional[dict]:
        if self.mesh_engine is None:
            return None
        if self._last_t is None:
            self._integrate(self.clock())
        row = self.mesh_engine.resize(n)
        return self._record("mesh_resize", **row)

    # -- the verdict ------------------------------------------------------ #

    def summary(self) -> dict:
        """The soak's closing ledger: what was done, what it cost, and
        how that compares to static provisioning at ``max_workers``."""
        import dataclasses
        with self._lock:
            self._integrate(self.clock())
            elapsed = ((self._last_t - self._t0)
                       if self._t0 is not None else 0.0)
            static = elapsed * self.policy.max_workers
            sizes = [p for _, p in self.trace] or [self.pool_size()]
            return {
                "policy": dataclasses.asdict(self.policy),
                "elapsed_s": elapsed,
                "chip_seconds": self._chip_seconds,
                "static_chip_seconds": static,
                "savings_fraction": (
                    1.0 - self._chip_seconds / static if static > 0
                    else 0.0),
                "workers_min": min(sizes),
                "workers_max": max(sizes),
                "scale_ups": sum(1 for a in self.actions
                                 if a["action"] == "scale_up"),
                "scale_downs": sum(1 for a in self.actions
                                   if a["action"] == "scale_down"),
                "paroles": sum(1 for a in self.actions
                               if a["action"] == "parole"
                               and a["outcome"] == "paroled"),
                "migrations": len(self.migrations),
                "actions": list(self.actions),
                "migration_rows": list(self.migrations),
                "trace": list(self.trace),
            }


__all__ = ["Actuator"]
