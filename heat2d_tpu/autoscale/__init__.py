"""Elastic capacity — the actuation layer that EXECUTES the control
plane's advice (docs/CONTROL.md "Actuation").

The control plane (heat2d_tpu/control/) has advised capacity since the
load-model PR (``load/capacity.advise`` sizing rows, discounted by mesh
quarantine), but nothing executed the advice — the fleet stayed the
size it was started at. This package closes that actuation gap:

- ``policy.AutoscalePolicy`` — the guardrails: min/max workers,
  per-direction cooldowns, the scale-down hold (hysteresis), step
  limits, drain timeout, parole passes.
- ``actuator.Actuator`` — turns one sizing row per control tick into
  at most a handful of concrete actions: ``FleetServer.add_worker``
  (warm-gated scale-up — a new worker is unroutable until compiled),
  ``FleetServer.retire_worker`` (fence-then-drain scale-down),
  ``HealthMonitor.parole`` (verified re-admission of quarantined
  devices), ``MeshEnsembleEngine.resize`` (voluntary mesh resize).
  It also keeps the chip-seconds ledger the "cheaper than static
  provisioning" verdict is computed from.
- ``migrate`` — live migration of long-running inverse jobs off a
  retiring worker: pause at an iteration boundary, checkpoint the
  Adam state (``diff.inverse.AdamState`` via ``resil.snapshot``),
  serialize it wire-style (base64 numpy, the ``fleet/wire`` idiom),
  resume on a survivor — bitwise-identical to an unmigrated run.

The CI gate (``autoscale-soak``) drives the whole loop under the
compressed diurnal profile from ``load/synth.py`` and asserts capacity
follows the envelope, SLOs hold through every resize, chip-hours land
below the static baseline, and one live-migrated job finishes bitwise
against its never-migrated oracle.
"""

from heat2d_tpu.autoscale.actuator import Actuator  # noqa: F401
from heat2d_tpu.autoscale.policy import AutoscalePolicy  # noqa: F401
