"""Autoscaler guardrails — the knobs between advice and action.

A raw ``capacity.advise`` row is a point-in-time estimate from a noisy
rate window; executing it verbatim would thrash the pool (spawn a
worker, retire it two ticks later, spawn again). The policy encodes
the standard stabilizers, all deliberately asymmetric in the
scale-down direction — adding capacity late costs latency, removing
it early costs correctness-adjacent churn (drains, migrations):

- hard bounds (``min_workers``/``max_workers`` — the static baseline
  the chip-hours ledger is judged against is ``max_workers``);
- per-direction cooldowns (a fresh scale-up must be allowed to absorb
  the load before the next resize is even considered);
- a consecutive-tick HOLD before any scale-down (``down_hold_ticks``:
  one quiet window is noise, N in a row is a trough);
- step limits per action (``max_step_up``/``max_step_down``).
"""

from __future__ import annotations

import dataclasses

from heat2d_tpu.mesh.health import PAROLE_PASSES


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Guardrails for one ``Actuator`` (module docstring)."""

    #: pool bounds; ``max_workers`` doubles as the static-provisioning
    #: baseline in the chip-seconds ledger
    min_workers: int = 1
    max_workers: int = 4
    #: seconds (actuator clock) between scale-ups / between scale-downs
    up_cooldown_s: float = 1.0
    down_cooldown_s: float = 3.0
    #: consecutive below-target observations before a scale-down is
    #: admitted (hysteresis — one quiet window is noise)
    down_hold_ticks: int = 3
    #: workers added / retired per action
    max_step_up: int = 2
    max_step_down: int = 1
    #: drain deadline for a retiring worker (then kill + replay)
    drain_timeout_s: float = 30.0
    #: consecutive verified probe passes a parole hearing requires
    parole_passes: int = PAROLE_PASSES

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1, got {self.min_workers}")
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= "
                f"min_workers ({self.min_workers})")
        if self.down_hold_ticks < 1:
            raise ValueError(
                f"down_hold_ticks must be >= 1, got "
                f"{self.down_hold_ticks}")
        if self.max_step_up < 1 or self.max_step_down < 1:
            raise ValueError("step limits must be >= 1")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.drain_timeout_s <= 0:
            raise ValueError(
                f"drain_timeout_s must be > 0, got "
                f"{self.drain_timeout_s}")
        if self.parole_passes < 1:
            raise ValueError(
                f"parole_passes must be >= 1, got {self.parole_passes}")
