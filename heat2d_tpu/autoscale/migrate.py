"""Live migration of long-running inverse jobs.

A worker being retired may be hours into an Adam recovery. Killing the
job and restarting from iteration 0 wastes the work; letting it pin
the worker defeats the drain. Migration threads the needle with the
machinery the repo already has:

1. **Pause** — ``diff.inverse.adam_minimize`` polls its ``pause``
   callback at iteration BOUNDARIES only, so the checkpoint always
   captures a consistent (params, m, v, iteration) tuple, host-copied
   through ``resil.snapshot_state(dtype=None)`` (exact, no dtype
   truncation).
2. **Ship** — the ``AdamState`` plus the full problem spec serialize
   into a JSON ticket with base64 numpy payloads (the ``fleet/wire``
   grid encoding idiom): the ticket IS a wire line, transportable to
   any survivor process.
3. **Resume** — ``resume_job`` rebuilds the problem from the spec and
   continues from the absolute iteration index. The host Adam update
   is a deterministic pure function of the state and the memoized
   compiled ``value_and_grad`` is jaxpr-pinned, so the migrated
   trajectory — every loss, every iterate — is BITWISE-identical to
   the run that never moved (the CI gate's oracle comparison).

``InverseJob`` is the thread-shaped handle the actuator drives: start,
pause-and-checkpoint, resume, join.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import threading
from typing import Callable, Optional

import numpy as np

from heat2d_tpu.diff.inverse import AdamState, InverseProblem

#: ticket schema tag — consumers refuse tickets they don't speak
MIGRATION_SCHEMA = "heat2d-tpu/inverse-migration/v1"


# -- wire-format encoding (the fleet/wire base64-numpy idiom) ---------- #

def _encode_array(a: Optional[np.ndarray]) -> Optional[dict]:
    if a is None:
        return None
    a = np.asarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(np.ascontiguousarray(a).tobytes())
                         .decode("ascii")}


def _decode_array(d: Optional[dict]) -> Optional[np.ndarray]:
    if d is None:
        return None
    a = np.frombuffer(base64.b64decode(d["b64"]),
                      dtype=np.dtype(d["dtype"]))
    return a.reshape([int(s) for s in d["shape"]]).copy()


def encode_state(state: AdamState) -> dict:
    """JSON-able form of an ``AdamState`` — exact: the arrays round-
    trip through raw bytes, never through decimal text."""
    return {"iteration": int(state.iteration),
            "params": _encode_array(state.params),
            "m": _encode_array(state.m),
            "v": _encode_array(state.v),
            "best": _encode_array(state.best),
            "best_loss": float(state.best_loss),
            "loss_history": [float(x) for x in state.loss_history],
            "grad_norm_history": [float(x) for x in
                                  state.grad_norm_history]}


def decode_state(d: dict) -> AdamState:
    return AdamState(
        iteration=int(d["iteration"]),
        params=_decode_array(d["params"]),
        m=_decode_array(d["m"]),
        v=_decode_array(d["v"]),
        best=_decode_array(d["best"]),
        best_loss=float(d["best_loss"]),
        loss_history=list(d["loss_history"]),
        grad_norm_history=list(d["grad_norm_history"]))


def problem_spec(problem: InverseProblem) -> dict:
    """JSON-able form of an ``InverseProblem`` (arrays base64)."""
    return {"nx": problem.nx, "ny": problem.ny,
            "steps": problem.steps, "target": problem.target,
            "obs_mask": _encode_array(np.asarray(problem.obs_mask)),
            "obs_values": _encode_array(
                np.asarray(problem.obs_values)),
            "cx": float(problem.cx), "cy": float(problem.cy),
            "u0": _encode_array(problem.u0),
            "reg": float(problem.reg), "adjoint": problem.adjoint,
            "segment": problem.segment, "method": problem.method}


def problem_from_spec(spec: dict) -> InverseProblem:
    return InverseProblem(
        nx=int(spec["nx"]), ny=int(spec["ny"]),
        steps=int(spec["steps"]), target=spec["target"],
        obs_mask=_decode_array(spec["obs_mask"]),
        obs_values=_decode_array(spec["obs_values"]),
        cx=float(spec["cx"]), cy=float(spec["cy"]),
        u0=_decode_array(spec.get("u0")),
        reg=float(spec.get("reg", 0.0)),
        adjoint=spec.get("adjoint", "checkpoint"),
        segment=spec.get("segment"),
        method=spec.get("method", "auto"))


def encode_ticket(problem: InverseProblem, state: AdamState, *,
                  iterations: int, lr: float,
                  tol: Optional[float] = None,
                  source_slot: Optional[int] = None) -> dict:
    """The migration ticket: everything a survivor needs to finish the
    job — problem, solve budget, and the mid-flight optimizer state."""
    return {"schema": MIGRATION_SCHEMA,
            "problem": problem_spec(problem),
            "solve": {"iterations": int(iterations), "lr": float(lr),
                      "tol": None if tol is None else float(tol)},
            "state": encode_state(state),
            "source_slot": source_slot}


def decode_ticket(doc) -> dict:
    """Accepts the ticket dict or its JSON line; validates the schema
    tag."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    if doc.get("schema") != MIGRATION_SCHEMA:
        raise ValueError(
            f"not an inverse-migration ticket: schema="
            f"{doc.get('schema')!r}")
    return doc


# -- the actuator's job handle ----------------------------------------- #

class InverseJob:
    """One long-running inverse solve on its own daemon thread, with a
    pause/checkpoint/resume surface (module docstring).

    The pause is COOPERATIVE: ``request_pause`` sets an event the
    optimizer polls at iteration boundaries, so ``checkpoint`` blocks
    at most one iteration (plus the compile, if the solve is still
    cold). A job that FINISHED before the pause landed checkpoints to
    ``None`` — the caller treats that as "nothing to migrate"."""

    def __init__(self, problem: InverseProblem, *,
                 iterations: int = 200, lr: float = 0.05,
                 tol: Optional[float] = None, registry=None,
                 state: Optional[AdamState] = None,
                 source_slot: Optional[int] = None):
        self.problem = problem
        self.iterations = int(iterations)
        self.lr = float(lr)
        self.tol = tol
        self.registry = registry
        self.source_slot = source_slot
        self._state = state
        self._pause_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.solution = None
        self.error: Optional[BaseException] = None

    def start(self) -> "InverseJob":
        if self._thread is not None:
            raise RuntimeError("job already started")
        self._thread = threading.Thread(
            target=self._run, name="heat2d-inverse-job", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        try:
            self.solution = self.problem.solve(
                iterations=self.iterations, lr=self.lr, tol=self.tol,
                registry=self.registry, state=self._state,
                pause=lambda _it: self._pause_evt.is_set())
        except BaseException as e:  # noqa: BLE001 — surfaced to caller
            self.error = e

    # -- state ---------------------------------------------------------- #

    def done(self) -> bool:
        t = self._thread
        return t is not None and not t.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
        if self.error is not None:
            raise self.error

    def completed_iterations(self) -> int:
        sol = self.solution
        return 0 if sol is None else int(sol.iterations)

    # -- migration ------------------------------------------------------ #

    def request_pause(self) -> None:
        self._pause_evt.set()

    def checkpoint(self, timeout: float = 120.0) -> Optional[dict]:
        """Pause at the next iteration boundary and return the wire
        ticket — or ``None`` if the job already finished (nothing to
        migrate; its ``solution`` stands)."""
        if self._thread is None:
            raise RuntimeError("job never started")
        self._pause_evt.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"inverse job did not reach an iteration boundary in "
                f"{timeout}s")
        if self.error is not None:
            raise self.error
        sol = self.solution
        if not sol.paused:
            return None
        return encode_ticket(self.problem, sol.state,
                             iterations=self.iterations, lr=self.lr,
                             tol=self.tol,
                             source_slot=self.source_slot)


def resume_job(ticket, *, registry=None) -> InverseJob:
    """Rebuild and START the job a ticket describes, on this (the
    survivor's) side of the wire. The total iteration budget and every
    solve knob ride in the ticket, so the finished trajectory is
    bitwise the unmigrated one's."""
    doc = decode_ticket(ticket)
    problem = problem_from_spec(doc["problem"])
    solve = doc["solve"]
    return InverseJob(
        problem, iterations=solve["iterations"], lr=solve["lr"],
        tol=solve.get("tol"), registry=registry,
        state=decode_state(doc["state"]),
        source_slot=doc.get("source_slot")).start()


def run_unmigrated(ticket_or_problem, *, iterations: int = 200,
                   lr: float = 0.05, tol: Optional[float] = None,
                   registry=None):
    """The ORACLE: the same solve, never paused, never moved. Accepts
    a ticket (budget read from it) or a bare problem (budget from the
    kwargs). Returns the ``InverseSolution``."""
    if isinstance(ticket_or_problem, InverseProblem):
        problem, solve = ticket_or_problem, {
            "iterations": iterations, "lr": lr, "tol": tol}
    else:
        doc = decode_ticket(ticket_or_problem)
        problem, solve = problem_from_spec(doc["problem"]), doc["solve"]
    return problem.solve(iterations=solve["iterations"],
                         lr=solve["lr"], tol=solve.get("tol"),
                         registry=registry)


__all__ = ["MIGRATION_SCHEMA", "InverseJob", "encode_state",
           "decode_state", "encode_ticket", "decode_ticket",
           "problem_spec", "problem_from_spec", "resume_job",
           "run_unmigrated"]


# keep the dataclass import obviously-used for linters that miss the
# annotation-only reference
_ = dataclasses
_ = Callable
