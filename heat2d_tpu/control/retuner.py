"""Continuous retuner — re-measure hot signatures off-peak, stage
candidates.

The fleet's router already knows per-signature demand (the
``fleet_signature_requests_total`` counters it feeds obs/slo.py with),
and ``tune/`` already owns measurement (``measure_candidate``) and
persistence (``TuningDB``). The retuner closes the gap between them:

1. **What to tune**: the hottest signatures by windowed request-count
   delta (cumulative counters differentiated per call, so a signature
   that WAS hot yesterday does not dominate forever).
2. **When to tune**: off-peak only — a measurement burns the same
   cores the workers serve on, so staging waits until the router's
   in-flight count sits at/below ``idle_inflight``.
3. **What it produces**: a CANDIDATE ``TuningDB`` at
   ``candidate_path``, stamped ``validated=False`` at epoch
   ``incumbent + 1`` (tune/db.py rollout provenance). Staging never
   touches the validated db — only a rollout's promote step does
   (control/rollout.py), and only after the canary proved the
   candidate bitwise-compatible and SLO-clean.

Measurement defaults to the deterministic ``SimulatedBackend`` (the
search logic is the subject here; CPU CI has no kernel worth
re-measuring) — pass ``backend=None`` explicitly to measure the
attached device.
"""

from __future__ import annotations

import ast
import logging
from typing import Optional

from heat2d_tpu.tune.db import TuningDB

log = logging.getLogger("heat2d_tpu.control")


def problem_from_signature(sig_str: str):
    """The tune-space ``Problem`` for a serve signature string, or
    None for signatures that carry no kernel shape to tune (inverse
    requests tune through their forward solves). Signature strings are
    ``str(req.signature())`` — literal Python tuples (the same
    contract load/replay.py parses)."""
    from heat2d_tpu.tune.space import Problem
    try:
        sig = ast.literal_eval(sig_str)
    except (ValueError, SyntaxError):
        return None
    if not isinstance(sig, tuple) or not sig or sig[0] == "inverse":
        return None
    try:
        nx, ny = int(sig[0]), int(sig[1])
        dtype = str(sig[3]) if len(sig) > 3 else "float32"
    except (TypeError, ValueError, IndexError):
        return None
    return Problem(nx, ny, dtype=dtype)


class Retuner:
    """Stage candidate tuning dbs for the control plane's rollouts.
    ``fleet`` needs only a registry and a ``_total_inflight``-bearing
    router surface (FleetServer, or a test double)."""

    def __init__(self, fleet, *, candidate_path: str,
                 validated_path: str, backend="simulated",
                 idle_inflight: int = 2, registry=None):
        from heat2d_tpu.obs.metrics import CounterDeltas
        from heat2d_tpu.tune.measure import SimulatedBackend
        self.fleet = fleet
        self.candidate_path = str(candidate_path)
        self.validated_path = str(validated_path)
        self.backend = (SimulatedBackend() if backend == "simulated"
                        else backend)
        self.idle_inflight = idle_inflight
        self.registry = (registry if registry is not None
                         else getattr(fleet, "registry", None))
        self._deltas = CounterDeltas()

    # -- the router's demand signal ------------------------------------- #

    def hot_signatures(self) -> list:
        """[(signature string, requests since last call)], hottest
        first, from the fleet's per-signature outcome counters."""
        reg = getattr(self.fleet, "registry", None)
        if reg is None:
            return []
        per_sig: dict = {}
        for k, d in self._deltas.tick(
                reg, "fleet_signature_requests_total").items():
            sig = dict(k).get("signature")
            if sig is not None and d > 0:
                per_sig[sig] = per_sig.get(sig, 0.0) + d
        return sorted(per_sig.items(), key=lambda p: -p[1])

    def off_peak(self) -> bool:
        """True when the router is idle enough that a measurement
        cannot contend with client traffic."""
        return (getattr(self.fleet, "_total_inflight", 0)
                <= self.idle_inflight)

    # -- staging --------------------------------------------------------- #

    def stage_candidate(self, sig_str: str) -> Optional[dict]:
        """Re-measure one signature's shape and stage the result as a
        candidate db. Returns the staging summary ({signature, problem,
        epoch, best, path}) or None when the signature has nothing to
        tune. The candidate db is seeded from the VALIDATED incumbent
        (shapes the retune did not touch keep their proven configs)
        and restamped ``validated=False`` at the next epoch."""
        problem = problem_from_signature(sig_str)
        if problem is None:
            return None
        from heat2d_tpu.tune.cli import search_problem

        incumbent = TuningDB(self.validated_path)
        candidate = TuningDB(self.candidate_path)
        # the candidate starts as a copy of the incumbent: a rollout
        # replaces the WHOLE db a worker loads, so untouched shapes
        # must ride along unchanged
        import copy as _copy
        candidate.data = _copy.deepcopy(incumbent.data)
        epoch = incumbent.epoch + 1
        import io
        summary = search_problem(candidate, problem,
                                 backend=self.backend,
                                 registry=self.registry,
                                 out=io.StringIO())
        candidate.mark_entries(validated=False, epoch=epoch)
        candidate.stamp_rollout(epoch=epoch, validated=False)
        candidate.save()
        if self.registry is not None:
            self.registry.counter("control_retunes_total")
        log.info("staged candidate epoch %d for %s at %s (best %s)",
                 epoch, sig_str, self.candidate_path,
                 summary.get("best"))
        return {"signature": sig_str, "problem": summary.get("problem"),
                "epoch": epoch, "best": summary.get("best"),
                "measured": summary.get("measured"),
                "path": self.candidate_path}
