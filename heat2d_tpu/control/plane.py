"""The SLO-driven control plane — telemetry -> decision -> action.

Every sensor and actuator this loop closes over already exists; the
plane is the policy that connects them, running beside the router in
the fleet supervisor process:

- sustained per-signature burn (``obs.slo.BurnWindow``) -> pre-emptive
  shed: lower the standard-priority watermark
  (``FleetServer.set_preemptive_shed``) so low-priority tenants shed
  BEFORE the ``DegradedMode`` breaker trips;
- sustained burn, fleet off-peak -> retune: stage a candidate db for
  the burning/hot signatures (``control.retuner``);
- sustained burn + a fitted capacity model (``load.capacity``) ->
  capacity advice: units needed for the observed rate vs deployed;
- staged candidate -> safe rollout: canary -> parity -> observe ->
  promote or auto-revert (``control.rollout``);
- burn clears -> lift the shed.

Each ``tick()`` is one evaluation pass (the background thread runs one
per ``interval``); every decision lands in the decision log the
``kind="control"`` run record carries, plus the ``control_*`` metric
families (docs/CONTROL.md has the table).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from heat2d_tpu.analysis.locks import AuditedLock
from heat2d_tpu.obs import slo

log = logging.getLogger("heat2d_tpu.control")


class ControlPlane:
    """The loop. ``policy`` judges per-signature burn; ``retuner`` is
    optional (without one, retune decisions are recorded as wanted but
    nothing stages); ``capacity_fit`` is a fitted model dict from
    ``load.capacity.fit_capacity`` (optional)."""

    def __init__(self, fleet, *, policy: Optional[slo.SLOPolicy] = None,
                 interval: float = 0.5,
                 burn_threshold: float = 1.0, sustain: int = 3,
                 shed_watermark: float = 0.4,
                 retuner=None, capacity_fit: Optional[dict] = None,
                 registry=None, mesh_health=None, sentinel=None,
                 actuator=None):
        """``sentinel``: an optional ``obs.perf.AnomalySentinel`` —
        each tick evaluates one sentinel window and its findings land
        in the decision log as ``perf_anomaly`` rows beside burn,
        closing telemetry->detection for performance regressions the
        SLO burn machinery can't see (a rate that quietly halved, a
        tail that grew inside its SLO, a roofline fraction that sagged).

        ``mesh_health``: an optional ``mesh.HealthMonitor`` — the
        device-quarantine book feeds capacity decisions: every
        quarantine transition lands in the decision log, the
        ``control_quarantined_devices`` gauge tracks the count, and
        sizing advice discounts deployed units by the surviving
        capacity fraction (7 of 8 chips alive = 7/8 of the modeled
        capacity actually serving).

        ``actuator``: an optional ``autoscale.Actuator`` — with one
        armed, sizing advice is EXECUTED, not just recorded: every
        tick (not only under sustained burn — trough scale-down needs
        the quiet ticks too) feeds the advice row to the actuator,
        which converges the worker pool toward it under its policy's
        guardrails. Actions taken land in the decision log as
        ``autoscale_*`` rows."""
        self.fleet = fleet
        self.actuator = actuator
        self.mesh_health = mesh_health
        self.sentinel = sentinel
        self._last_quarantined: Optional[int] = None
        self._last_quarantine_seq = 0
        self.policy = policy or slo.SLOPolicy(latency_p99_s=30.0)
        self.interval = interval
        self.shed_watermark = shed_watermark
        self.retuner = retuner
        self.capacity_fit = capacity_fit
        from heat2d_tpu.obs.metrics import CounterDeltas
        self.registry = (registry if registry is not None
                         else getattr(fleet, "registry", None))
        self.burn = slo.BurnWindow(self.policy, prefix="fleet",
                                   threshold=burn_threshold,
                                   sustain=sustain)
        self._deltas = CounterDeltas()
        self.decisions: list = []
        self.rollouts: list = []
        self.staged: list = []
        self.retune_wanted: set = set()
        #: signatures already attempted this burn episode — staging is
        #: once per episode, not once per tick (cleared when the burn
        #: clears, so a future episode may re-stage)
        self._retuned: set = set()
        self._shed_active = False
        self._burning = False
        self._last_advice_units = None
        self._rollout_active = False
        self._last_t = None
        self._lock = AuditedLock("control.plane")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------- #

    def start(self) -> "ControlPlane":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="heat2d-control-plane",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._shed_active:
            # never leave a stopped plane's shed in force
            self.fleet.set_preemptive_shed(None)
            self._shed_active = False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                # the plane is an OPERATOR, not a dependency: a broken
                # tick must not take serving down with it
                log.exception("control tick failed")

    # -- the loop body --------------------------------------------------- #

    def _decide(self, action: str, **fields) -> None:
        row = {"t": time.monotonic(), "action": action, **fields}
        with self._lock:
            self.decisions.append(row)
        if self.registry is not None:
            self.registry.counter("control_actions_total",
                                  action=action)
        log.info("control decision: %s %s", action, fields or "")

    def _observed_rps(self) -> float:
        """Fleet-wide completion rate since the previous tick."""
        reg = self.registry
        if reg is None:
            return 0.0
        done = sum(d for k, d in self._deltas.tick(
            reg, "fleet_requests_total").items()
            if dict(k).get("outcome") == "completed")
        now = time.monotonic()
        last_t, self._last_t = self._last_t, now
        if last_t is None or now <= last_t:
            return 0.0
        return max(0.0, done) / (now - last_t)

    def tick(self) -> Dict[str, dict]:
        """One telemetry->decision->action pass; returns the burn
        window's result (test hook)."""
        res = self.burn.tick(self.registry)
        sustained = self.burn.sustained(res)
        if self.registry is not None:
            self.registry.gauge("control_burning_signatures",
                                len(sustained))
        rps = self._observed_rps()

        capacity_fraction = 1.0
        if self.mesh_health is not None:
            # device quarantine -> capacity decisions: transitions are
            # decision rows (deduped like shed/unshed — an hour of a
            # quarantined chip is ONE row), the live count is a gauge,
            # and the capacity fraction discounts the sizing advice.
            # Per-tick reads use the cheap accessors; the full event
            # book is copied only on a transition.
            q = len(self.mesh_health.quarantined())
            capacity_fraction = self.mesh_health.capacity_fraction()
            if self.registry is not None:
                self.registry.gauge("control_quarantined_devices",
                                    float(q))
            if self._last_quarantined is None:
                # startup: a healthy mesh needs no "nothing is
                # quarantined" decision row, but quarantines that
                # PRE-DATE the plane (a restart mid-incident) are
                # state the audit trail must carry — baseline at 0 so
                # a nonzero first tick logs them like any transition
                self._last_quarantined = 0
            if q != self._last_quarantined:
                snap = self.mesh_health.snapshot()
                # only the events of THIS transition (seq past the
                # last logged fence): a mesh losing chips one by one
                # logs each conviction once, not a growing history
                fresh = [e for e in snap["events"]
                         if e["seq"] > self._last_quarantine_seq]
                self._decide("device_quarantine",
                             quarantined=snap["quarantined"],
                             capacity_fraction=capacity_fraction,
                             events=[{"device": e["device"],
                                      "reason": e["reason"]}
                                     for e in fresh])
                if fresh:
                    self._last_quarantine_seq = max(
                        e["seq"] for e in fresh)
                self._last_quarantined = q

        if self.sentinel is not None and self.registry is not None:
            # one sentinel window per tick: EWMA+MAD findings are
            # decision rows beside burn (obs/perf.AnomalySentinel) —
            # detection only; actuation stays with the burn machinery
            for f in self.sentinel.tick(self.registry):
                self._decide("perf_anomaly", **f)

        if sustained and not self._shed_active:
            # escalate BEFORE the breaker: shed the low-priority
            # tenants while priority-0 traffic and cache hits keep
            # answering
            self.fleet.set_preemptive_shed(self.shed_watermark)
            self._shed_active = True
            self._decide("shed", watermark=self.shed_watermark,
                         signatures=sustained)
        elif not sustained and self._shed_active:
            self.fleet.set_preemptive_shed(None)
            self._shed_active = False
            self._retuned.clear()       # a new episode may retune
            self._decide("unshed")
        if self.registry is not None:
            self.registry.gauge("control_shed_active",
                                1.0 if self._shed_active else 0.0)

        if sustained:
            fresh = [s for s in sustained
                     if s not in self.retune_wanted
                     and s not in self._retuned]
            if fresh:
                self.retune_wanted.update(fresh)
                self._decide("retune_wanted", signatures=fresh)
        advice = None
        if self.capacity_fit and (sustained
                                  or self.actuator is not None):
            from heat2d_tpu.load import capacity
            # with an actuator armed, "deployed" means the provisioned
            # pool (retired slots excluded), not merely whoever is
            # alive this instant mid-restart
            current = (self.actuator.pool_size()
                       if self.actuator is not None
                       else len(self.fleet.sup.alive_slots()))
            advice = capacity.advise(self.capacity_fit, rps, current)
            if capacity_fraction < 1.0:
                # quarantined chips don't serve: the deployed
                # units' EFFECTIVE capacity shrinks by the
                # surviving fraction, so the add-units gap grows
                advice["capacity_fraction"] = capacity_fraction
                advice["effective_units"] = (
                    advice["current_units"] * capacity_fraction)
                need = advice.get("needed_units")
                if need is not None:
                    import math
                    advice["add_units"] = max(
                        0, math.ceil(
                            need - advice["effective_units"]))
            # advice rows dedupe on state transitions (like shed/
            # unshed): an hour-long burn must not append thousands
            # of identical rows to the decision log. The key
            # includes add_units so a mid-burn quarantine that
            # shrinks effective capacity (same needed_units,
            # bigger gap) emits the corrected advice. Quiet-tick
            # advice (actuator-only) stays out of the decision log —
            # the ACTIONS it triggers are the record.
            if sustained:
                advice_key = (advice.get("needed_units"),
                              advice.get("add_units"))
                if (not self._burning
                        or advice_key != self._last_advice_units):
                    self._decide("capacity_advice", **advice)
                    self._last_advice_units = advice_key
            if (self.registry is not None
                    and advice.get("needed_units")):
                self.registry.gauge("control_capacity_needed_units",
                                    advice["needed_units"])
        self._burning = bool(sustained)

        if self.actuator is not None:
            # execution: the actuator converges the pool toward the
            # advice under its guardrails; every action it takes is a
            # decision row (autoscale_scale_up / autoscale_scale_down)
            for row in self.actuator.observe(advice):
                fields = {k: v for k, v in row.items()
                          if k not in ("t", "action")}
                self._decide(f"autoscale_{row['action']}", **fields)

        # no staging while a rollout is live: stage_candidate rewrites
        # candidate_path, and the rollout's promote guard would (
        # correctly) revert on the epoch change — don't invite it
        if self.retuner is not None and self.retune_wanted \
                and not self._rollout_active \
                and self.retuner.off_peak():
            staged = None
            for sig in sorted(self.retune_wanted):
                staged = self.retuner.stage_candidate(sig)
                if staged is not None:
                    break
            # one attempt per burn episode, staged or not: a sustained
            # burn must not re-run the search every idle tick
            self._retuned.update(self.retune_wanted)
            self.retune_wanted.clear()
            if staged is not None:
                with self._lock:
                    self.staged.append(staged)
                self._decide("retune_staged", **staged)
        return res

    # -- rollouts -------------------------------------------------------- #

    def run_rollout(self, cfg) -> dict:
        """Run one safe rollout (control/rollout.py) and record its
        outcome. The caller supplies the RolloutConfig (probe spec,
        candidate/validated paths, observation knobs)."""
        from heat2d_tpu.control.rollout import Rollout
        self._decide("rollout", epoch=_db_epoch(cfg.candidate_path))
        self._rollout_active = True
        try:
            out = Rollout(self.fleet, cfg, policy=self.policy,
                          registry=self.registry).run()
        finally:
            self._rollout_active = False
        with self._lock:
            self.rollouts.append(out)
        return out

    # -- the record ------------------------------------------------------ #

    def serving_invariant(self, gens=None) -> dict:
        """The chaos gate's assertion: across every worker generation
        the supervisor ever saw ready, only generations spawned BY a
        rollout (``via="rollout"`` with an env overlay) may report a
        non-validated tune db — a crash/monitor restart must always
        rejoin on the validated incumbent. Pass ``gens`` to evaluate
        an already-taken snapshot (``summary()`` does, so its verdict
        and the generation log it rides with describe the SAME set)."""
        if gens is None:
            gens = self.fleet.sup.generations_snapshot()
        violations = [
            g for g in gens
            if not (g.get("via") == "rollout" and g.get("overlay"))
            and g.get("tune") is not None
            and not g["tune"].get("validated", True)]
        return {"generations": len(gens),
                "unvalidated_serving": violations,
                "no_unvalidated_serving": not violations}

    def summary(self) -> dict:
        """The ``kind="control"`` run-record payload."""
        with self._lock:
            out = {
                "decisions": list(self.decisions),
                "rollouts": list(self.rollouts),
                "staged": list(self.staged),
                "shed_active": self._shed_active,
            }
        gens = self.fleet.sup.generations_snapshot()
        out.update(self.serving_invariant(gens))
        out["generation_log"] = gens
        if self.actuator is not None:
            out["autoscale"] = self.actuator.summary()
        return out


def _db_epoch(path: str) -> int:
    """The epoch stamp of the db at ``path`` (0 when absent)."""
    from heat2d_tpu.tune.db import TuningDB
    return TuningDB(path).epoch
