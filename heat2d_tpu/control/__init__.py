"""Fleet control plane — close the loop from live telemetry back into
tuning, shedding and capacity decisions (docs/CONTROL.md).

- ``plane.ControlPlane`` — the tick loop: sustained SLO burn
  (obs/slo.BurnWindow) -> pre-emptive shed / retune / capacity advice,
  escalating before the DegradedMode breaker trips.
- ``retuner.Retuner`` — re-measure hot signatures off-peak and stage
  candidate TuningDBs (validated=False, next epoch).
- ``rollout.Rollout`` — canary one worker, assert bitwise parity,
  observe SLO burn + relative latency, promote worker-by-worker or
  auto-revert with a bitwise post-revert proof; kill-storm-safe by
  construction (one-generation env overlays).
"""

from heat2d_tpu.control.plane import ControlPlane
from heat2d_tpu.control.retuner import Retuner, problem_from_signature
from heat2d_tpu.control.rollout import Rollout, RolloutConfig

__all__ = ["ControlPlane", "Retuner", "Rollout", "RolloutConfig",
           "problem_from_signature"]
