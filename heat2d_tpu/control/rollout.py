"""Safe tuning rollout — canary, bitwise parity, observation,
promote-or-revert.

A staged candidate db (control/retuner.py) reaches the fleet
worker-by-worker, and every gate between the stages is MEASURED, not
scheduled:

1. **baseline** — probe an incumbent worker (``FleetServer.probe``:
   targeted, cache-bypassing) and keep its answer bytes: the bitwise
   reference every later parity check compares against.
2. **canary** — restart ONE worker with the candidate db handed in as
   a one-generation env overlay (``Supervisor.restart_worker``). The
   overlay is the safety property: a crash restart — including a kill
   storm landing right now — rebuilds the worker env from the durable
   config, so the failure path can only ever resurrect the VALIDATED
   incumbent, never the candidate.
3. **parity** — the canary must answer the probe bitwise-identically
   to the incumbent. A tuned config that changes a single bit is a
   different program, not a faster one; mismatch reverts immediately.
4. **observe** — for ``observe_s``, paired canary/incumbent probes
   measure relative latency while a ``BurnWindow`` (obs/slo.py) watches
   the fleet's per-signature SLO burn. A sustained burn, a canary
   latency regression past ``latency_ratio`` x the incumbent, a probe
   failure, or the canary LOSING ITS CANDIDATE (a storm restarted it
   onto the incumbent — nothing left to observe) all revert.
5. **promote** — the candidate is stamped ``validated`` at its epoch,
   atomically becomes the content of the validated path, and the
   remaining workers (canary included — it still points at the
   candidate FILE) are deliberately restarted one at a time onto the
   durable env. Every restart from here on, deliberate or crash,
   loads the newly validated db.
6. **revert** — the canary is restarted onto the durable env (if a
   storm has not already done so) and re-probed: the post-revert
   answer must be BITWISE the pre-rollout baseline, asserted in the
   outcome the CI control-gate greps.

``resil.chaos.rollout_point`` is announced at each window boundary so
a chaos campaign (``HEAT2D_CHAOS_ROLLOUT_KILL_PHASE``) can land a
kill storm at the worst possible moment; the storm callback kills
workers through the supervisor, never the control plane itself.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

from heat2d_tpu.obs import slo
from heat2d_tpu.resil import chaos
from heat2d_tpu.resil.retry import wait_for
from heat2d_tpu.serve.schema import Rejected, SolveRequest
from heat2d_tpu.tune.db import TuningDB

log = logging.getLogger("heat2d_tpu.control")


@dataclasses.dataclass(frozen=True)
class RolloutConfig:
    """One rollout's knobs. ``probe_spec`` is the canonical request
    dict (serve/schema.py) parity and latency probes solve;
    ``extra_canary_env`` rides the canary's one-generation overlay
    (the CI gate injects a deliberately-bad candidate through it —
    ``HEAT2D_CHAOS_SLOW_WORKER_S``-style)."""

    candidate_path: str
    validated_path: str
    probe_spec: dict
    observe_s: float = 2.0
    observe_probes: int = 4
    latency_ratio: float = 3.0
    latency_floor_s: float = 0.25
    burn_threshold: float = 1.0
    sustain: int = 2
    probe_timeout: float = 60.0
    ready_timeout: float = 120.0
    extra_canary_env: dict = dataclasses.field(default_factory=dict)


def _median(xs: list) -> float:
    s = sorted(xs)
    return s[len(s) // 2] if s else float("inf")


class Rollout:
    """Execute one rollout end to end (``run()``); the control plane
    threads it beside live traffic. All decisions and probe digests
    land in the returned summary — the ``rollouts`` rows of the
    ``kind="control"`` run record."""

    def __init__(self, fleet, cfg: RolloutConfig, *,
                 policy: Optional[slo.SLOPolicy] = None, registry=None):
        self.fleet = fleet
        self.cfg = cfg
        self.policy = policy or slo.SLOPolicy(latency_p99_s=30.0)
        self.registry = (registry if registry is not None
                         else getattr(fleet, "registry", None))
        self.out: dict = {"phases": [], "outcome": None,
                          "canary": None, "epoch": None,
                          "post_revert_parity": None}
        self._pre_bytes: Optional[bytes] = None

    # -- helpers -------------------------------------------------------- #

    def _phase(self, name: str, **fields) -> None:
        self.out["phases"].append({"phase": name, **fields})
        log.info("rollout phase %s %s", name, fields or "")

    def _storm_cb(self, n: int):
        """The chaos hook's kill action: hard-kill ``n`` alive workers
        (0 = all) through the supervisor — the monitor's normal death
        path then fences, replays, and restarts them from the DURABLE
        env."""
        alive = self.fleet.sup.alive_slots()
        targets = alive if not n else alive[:n]
        log.warning("chaos storm: killing worker(s) %s mid-rollout",
                    targets)
        for s in targets:
            self.fleet.sup.kill_worker(s)

    def _probe(self, slot: int):
        """(bytes, latency_s) of one targeted probe, or (None, reason)
        on failure."""
        import numpy as np
        req = SolveRequest.from_dict(dict(self.cfg.probe_spec))
        t0 = time.monotonic()
        try:
            res = self.fleet.probe(
                slot, req, timeout=self.cfg.probe_timeout).result(
                self.cfg.probe_timeout + 30)
        except Rejected as e:
            return None, e.code
        except Exception as e:  # noqa: BLE001 — a probe failure is a
            #                     rollout decision, not a crash
            return None, repr(e)
        return np.asarray(res.u).tobytes(), time.monotonic() - t0

    def _wait_ready(self, slot: int, *, want_path: Optional[str],
                    deadline_s: float) -> Optional[dict]:
        """Poll until ``slot`` is alive+ready (and, when ``want_path``
        is given, reporting that tune-db path). Returns the worker's
        ready info, or None on timeout. Deadline semantics via
        ``resil.retry.wait_for`` — the one injectable-clock dispatch-
        guard convention (the supervisor's clock, when it has one)."""
        found: list = []

        def check() -> bool:
            if slot not in self.fleet.sup.alive_slots():
                return False
            info = self.fleet.sup.worker_info(slot)
            if info is None:
                return False
            path = (info.get("tune") or {}).get("path")
            if want_path is None or path == want_path:
                found.append(info)
                return True
            return False

        if wait_for(check, deadline_s, clock=self.fleet.sup.clock,
                    poll=0.05):
            return found[-1]
        return None

    def _canary_still_candidate(self, slot: int) -> bool:
        info = self.fleet.sup.worker_info(slot)
        return (info is not None
                and (info.get("tune") or {}).get("path")
                == self.cfg.candidate_path)

    def _count_outcome(self, outcome: str) -> None:
        if self.registry is not None:
            self.registry.counter("control_rollouts_total",
                                  outcome=outcome)

    # -- the state machine ---------------------------------------------- #

    def run(self) -> dict:
        cfg = self.cfg
        candidate = TuningDB(cfg.candidate_path)
        self.out["epoch"] = candidate.epoch
        if candidate.validated:
            return self._abort("candidate is already validated — "
                               "nothing to roll out")
        alive = self.fleet.sup.alive_slots()
        if len(alive) < 2:
            return self._abort(
                f"need >= 2 alive workers to canary (have "
                f"{len(alive)}): an incumbent must keep serving while "
                f"the canary proves itself")
        canary, incumbent = alive[-1], alive[0]
        self.out["canary"] = canary

        # 1 -- baseline: the bitwise reference, from an incumbent
        pre, lat = self._probe(incumbent)
        if pre is None:
            return self._abort(f"baseline probe failed: {lat}")
        self._pre_bytes = pre
        self._phase("baseline", incumbent=incumbent,
                    latency_s=round(lat, 6))

        # 2 -- canary: one worker, candidate db, ONE-generation overlay
        chaos.rollout_point("canary", self._storm_cb)
        overlay = {"HEAT2D_TUNE_DB": cfg.candidate_path,
                   **cfg.extra_canary_env}
        self.fleet.sup.restart_worker(canary, env_overlay=overlay)
        info = self._wait_ready(canary, want_path=cfg.candidate_path,
                                deadline_s=cfg.ready_timeout)
        if info is None:
            # a storm may have raced the spawn: whatever runs in the
            # slot now came from the durable env — revert formally so
            # the record carries the post-revert parity proof
            return self._revert(canary, "canary_never_ready")
        tune = info.get("tune") or {}
        self._phase("canary", slot=canary, tune=tune,
                    overlay_keys=sorted(overlay))
        if tune.get("validated", True) or tune.get("epoch") \
                != candidate.epoch:
            return self._revert(canary, "canary_stamp_mismatch")

        # 3 -- parity: bitwise, or it never rolls
        chaos.rollout_point("parity", self._storm_cb)
        got, lat = self._probe(canary)
        if got is None:
            return self._revert(canary, f"parity_probe_failed:{lat}")
        match = got == pre
        if self.registry is not None:
            self.registry.counter("control_probe_parity_total",
                                  result="match" if match
                                  else "mismatch")
        self._phase("parity", match=match, latency_s=round(lat, 6))
        if not match:
            return self._revert(canary, "parity_mismatch")

        # 4 -- observe: paired probes + windowed SLO burn
        chaos.rollout_point("observe", self._storm_cb)
        burn = slo.BurnWindow(self.policy, prefix="fleet",
                              threshold=cfg.burn_threshold,
                              sustain=cfg.sustain)
        burn.tick(self.registry)            # baseline window
        can_lat, inc_lat = [], []
        pause = max(0.05, cfg.observe_s / max(1, cfg.observe_probes))
        t_end = time.monotonic() + cfg.observe_s
        while True:
            time.sleep(pause)
            if not self._canary_still_candidate(canary):
                # a storm took the canary: its replacement rejoined on
                # the durable (validated) env — by construction nothing
                # unvalidated is serving, and there is nothing left to
                # observe
                return self._revert(canary, "canary_lost_in_storm")
            b, lc = self._probe(canary)
            if b is None:
                return self._revert(canary, f"canary_probe_failed:{lc}")
            if b != pre:
                return self._revert(canary, "parity_drift_in_observe")
            _b2, li = self._probe(incumbent)
            if _b2 is not None:
                inc_lat.append(li)
            can_lat.append(lc)
            sustained = burn.sustained(burn.tick(self.registry))
            if sustained:
                self._phase("observe", burned=sustained)
                return self._revert(canary, "slo_burn")
            if time.monotonic() >= t_end:
                break
        if not inc_lat:
            # no incumbent sample landed (it died/restarted all
            # window): there is no baseline to judge the canary
            # against, and "no evidence" reverts — an unbounded bar
            # would wave an arbitrarily slow canary through
            return self._revert(canary, "no_incumbent_latency")
        cm, im = _median(can_lat), _median(inc_lat)
        bar = max(cfg.latency_ratio * im, cfg.latency_floor_s)
        self._phase("observe", canary_median_s=round(cm, 6),
                    incumbent_median_s=round(im, 6),
                    bar_s=round(bar, 6), probes=len(can_lat))
        if cm > bar:
            return self._revert(canary, "latency_regression")

        # 5 -- promote: candidate becomes the validated epoch, then
        # every worker deliberately restarts onto it, one at a time
        chaos.rollout_point("promote", self._storm_cb)
        candidate = TuningDB(cfg.candidate_path)
        if candidate.epoch != self.out["epoch"] or candidate.validated:
            # the file changed under us (a concurrent re-stage, an
            # external writer): whatever it now holds was NEVER
            # canaried — promoting it would validate unproven content
            return self._revert(canary, "candidate_changed_mid_rollout")
        candidate.mark_entries(validated=True, epoch=candidate.epoch)
        candidate.stamp_rollout(epoch=candidate.epoch, validated=True)
        candidate.save()
        validated = TuningDB(cfg.validated_path)
        import copy as _copy
        validated.data = _copy.deepcopy(candidate.data)
        validated.save()        # atomic: tmp + fsync + os.replace
        if self.registry is not None:
            self.registry.gauge("control_epoch", candidate.epoch)
        self._phase("promote", epoch=candidate.epoch)
        rolled = []
        for slot in list(self.fleet.sup.alive_slots()):
            # the canary re-rolls too: it must leave the candidate
            # FILE for the validated path like everyone else
            self.fleet.sup.restart_worker(slot)
            if self._wait_ready(slot, want_path=None,
                                deadline_s=cfg.ready_timeout) is None:
                log.warning("slot %d slow to rejoin after promote "
                            "(the monitor will keep restarting it)",
                            slot)
            rolled.append(slot)
        self._phase("roll", slots=rolled)
        self.out["outcome"] = "promoted"
        self._count_outcome("promoted")
        return self.out

    # -- failure exits --------------------------------------------------- #

    def _abort(self, reason: str) -> dict:
        """Pre-canary failure: nothing was changed, nothing to revert."""
        self._phase("abort", reason=reason)
        self.out["outcome"] = f"aborted:{reason.split(' ')[0]}"
        self.out["reason"] = reason
        self._count_outcome("aborted")
        return self.out

    def _revert(self, canary: int, reason: str) -> dict:
        """Auto-revert: put the canary back on the durable (validated)
        env — unless a storm already did — and PROVE the revert with a
        bitwise post-revert probe against the pre-rollout baseline.
        The still-candidate check re-runs AFTER every wait: a canary
        whose spawn outlived its ready window surfaces the candidate
        db only once it finally reports ready, and leaving it serving
        would be exactly the non-validated leak this subsystem
        exists to prevent."""
        log.warning("rollout auto-revert: %s", reason)
        deadline = time.monotonic() + self.cfg.ready_timeout
        post = None
        while True:
            if self._canary_still_candidate(canary):
                self.fleet.sup.restart_worker(canary)
            left = deadline - time.monotonic()
            if left <= 0:
                break
            if self._wait_ready(canary, want_path=None,
                                deadline_s=left) is None:
                break           # never came up: parity stays unproven
            if self._canary_still_candidate(canary):
                continue        # late candidate spawn: restart it
            post, _lat = self._probe(canary)
            break
        parity = (post is not None and self._pre_bytes is not None
                  and post == self._pre_bytes)
        self.out["post_revert_parity"] = parity
        self._phase("revert", reason=reason, parity=parity)
        self.out["outcome"] = f"reverted:{reason}"
        self._count_outcome("reverted")
        if self.registry is not None:
            self.registry.counter("control_probe_parity_total",
                                  result="match" if parity
                                  else "mismatch")
        return self.out
