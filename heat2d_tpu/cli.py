"""Command-line driver — the reference's four ``main()``s as one CLI.

The reference's programs take no arguments; every knob is a compile-time
``#define`` and the run recipes live in readme.md:9-19 (mpicc/mpiexec/nvcc
lines). Here the same knobs are flags with the same names and defaults, and
the three run modes are subcommand-free ``--mode`` choices:

    heat2d-tpu --mode serial                       # 1-task reference run
    heat2d-tpu --mode pallas --nxprob 640 --nyprob 1024 --steps 10000
    heat2d-tpu --mode dist2d --gridx 2 --gridy 2   # mpiexec -n 4 analogue
    heat2d-tpu --mode dist1d --numworkers 4

Outputs mirror the reference: ``initial.dat``/``final.dat`` text dumps
(rowmajor layout by default, ``--dat-layout baseline`` for the
mpi_heat2Dn.c orientation — SURVEY.md A.6), optional binary dumps, startup
banner and ``Elapsed time: %e sec`` line (grad1612_mpi_heat.c:66-69, 287),
plus a structured JSON run record the reference lacked (SURVEY.md §5.5).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from heat2d_tpu.config import ConfigError, HeatConfig
from heat2d_tpu.vocab import PROBLEMS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu",
        description="TPU-native 2D heat-equation solver "
                    "(capabilities of patschris/Heat2D)")
    p.add_argument("--mode", default="serial",
                   choices=["serial", "pallas", "dist1d", "dist2d", "hybrid"])
    p.add_argument("--method", default="explicit",
                   choices=["explicit", "adi", "mg"],
                   help="time-stepping scheme (docs/ALGORITHMS.md): "
                        "explicit forward Euler (stability-limited "
                        "cx+cy <= 1/2), Crank-Nicolson ADI on batched "
                        "tridiagonal solves, or multigrid-solved CN — "
                        "the implicit schemes are unconditionally "
                        "stable, so --cx/--cy become dt-scaled "
                        "diffusion numbers chosen by accuracy")
    g = p.add_argument_group("problem (reference #define names)")
    g.add_argument("--problem", default="heat5", choices=list(PROBLEMS),
                   help="spatial-operator family (problem registry, "
                        "docs/PROBLEMS.md): heat5 is the reference "
                        "5-point stencil (byte-identical to the "
                        "pre-registry solver); other families run the "
                        "registry's kernels with per-family stability "
                        "bounds and capability gating")
    g.add_argument("--nxprob", type=int, default=10)
    g.add_argument("--nyprob", type=int, default=10)
    g.add_argument("--steps", type=int, default=100)
    g.add_argument("--cx", type=float, default=0.1)
    g.add_argument("--cy", type=float, default=0.1)
    d = p.add_argument_group("decomposition")
    d.add_argument("--gridx", type=int, default=1)
    d.add_argument("--gridy", type=int, default=1)
    d.add_argument("--numworkers", type=int, default=None,
                   help="dist1d row-strip count (defaults to --gridx)")
    d.add_argument("--strict-baseline", action="store_true",
                   help="enforce mpi_heat2Dn.c's 3..8 worker range")
    d.add_argument("--halo-depth", type=int, default=None,
                   help="wide-halo depth T for distributed modes: one "
                        "T-deep ghost exchange per T steps (default auto; "
                        "1 = the reference's per-step exchange)")
    d.add_argument("--halo", default="collective",
                   choices=["collective", "fused"],
                   help="halo-exchange route: 'collective' = exchange-"
                        "then-compute (a ppermute barrier per chunk); "
                        "'fused' = overlap edge communication with the "
                        "interior sweep (in-kernel ICI async copies on "
                        "TPU, explicit inner/boundary split elsewhere; "
                        "bitwise-identical results, degrades to "
                        "collective where unsupported — docs/SCALING.md)")
    e = p.add_argument_group(
        "ensemble (batched parameter sweep — one launch advances every "
        "(cx, cy) member; the reference needed one compile+run per "
        "configuration). Sharding model: distributed modes shard MEMBERS "
        "over all devices on a batch mesh axis; VMEM-sized members run "
        "in the batched resident kernel, bigger ones stream through the "
        "band kernel. Members too big for ONE device compose batch x "
        "spatial: --mode dist2d --gridx/--gridy decomposes each member "
        "over its own spatial submesh of a ('b', x, y) mesh")
    e.add_argument("--ensemble-cx", default=None, metavar="LIST",
                   help="comma-separated cx values; with --ensemble-cy "
                        "runs the whole batch in one compiled program")
    e.add_argument("--ensemble-cy", default=None, metavar="LIST",
                   help="comma-separated cy values (same length as "
                        "--ensemble-cx)")
    c = p.add_argument_group("convergence")
    c.add_argument("--convergence", action="store_true")
    c.add_argument("--interval", type=int, default=20)
    c.add_argument("--sensitivity", type=float, default=0.1)
    o = p.add_argument_group("output")
    o.add_argument("--outdir", default=".")
    o.add_argument("--dat-layout", default="rowmajor",
                   choices=["rowmajor", "baseline", "none"],
                   help="text dump layout; 'baseline' matches "
                        "mpi_heat2Dn.c prtdat orientation")
    o.add_argument("--binary-dumps", action="store_true",
                   help="also write initial_binary.dat/final_binary.dat "
                        "(MPI-IO byte format)")
    o.add_argument("--checkpoint", default=None,
                   help="path to write a loadable checkpoint of the final "
                        "state. An existing DIRECTORY (or a path ending "
                        "in '/') selects managed mode: crash-consistent "
                        "snapshots under a manifest with retention and "
                        "torn-entry fallback (resil/, docs/RESILIENCE.md)")
    o.add_argument("--checkpoint-every", type=int, default=None,
                   metavar="K",
                   help="with --checkpoint: also write a restart point "
                        "every K steps (periodic failure-recovery hook "
                        "the reference lacked — SURVEY.md 5.3/5.4). "
                        "Snapshots are written ASYNC, off the timed "
                        "segments (resil.AsyncCheckpointer)")
    o.add_argument("--checkpoint-keep", type=int, default=3, metavar="N",
                   help="managed (directory) checkpoints retained before "
                        "old snapshots are garbage-collected (0 = keep "
                        "all)")
    o.add_argument("--resume", default=None,
                   help="checkpoint to resume from (remaining steps "
                        "run): a checkpoint file, or a checkpoint "
                        "DIRECTORY — resumes from the newest snapshot "
                        "that loads verified, falling back past "
                        "torn/corrupt entries")
    o.add_argument("--run-record", default=None,
                   help="path for the JSON run record")
    o.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write the run's telemetry as JSONL (metrics "
                        "registry events + snapshot + the unified run "
                        "record); on convergence runs this also enables "
                        "in-loop residual streaming out of the compiled "
                        "loop (obs/ subsystem). Off by default: the "
                        "timed hot path is byte-identical without it")
    o.add_argument("--profile", default=None, metavar="LOGDIR",
                   help="capture a jax.profiler device trace of the timed "
                        "run (the mpiP analogue; digest it with "
                        "heat2d-tpu-prof LOGDIR, or view with "
                        "tensorboard --logdir / ui.perfetto.dev)")
    o.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="arm distributed tracing (obs/tracing.py): "
                        "host-side spans — run root, phase() entries — "
                        "land as JSONL in DIR; merge with "
                        "heat2d-tpu-trace DIR. Opt-in and free when "
                        "off: the compiled programs are byte-identical "
                        "either way. The run record gains trace_id")
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"],
                   help="python logging level for the heat2d_tpu loggers")
    p.add_argument("--accum-dtype", default="float32",
                   choices=["float32", "float64"],
                   help="float64 mirrors the C reference's double promotion")
    p.add_argument("--bitwise-parity", action="store_true",
                   help="pallas/hybrid modes: use the literal reference "
                        "stencil expression instead of the faster FMA "
                        "factoring, making results bitwise identical to "
                        "--mode serial (serial/dist1d/dist2d already are)")
    p.add_argument("--vmem-budget", type=int, default=None, metavar="MiB",
                   help="per-core VMEM size in MiB to plan kernels against, "
                        "overriding the value derived from the detected "
                        "device kind (v5e: 16); HEAT2D_VMEM_BUDGET is the "
                        "env twin, and the active source (default/flag/"
                        "env/probe/db) is recorded in the run record")
    p.add_argument("--debug", action="store_true")
    p.add_argument("--device-info", action="store_true",
                   help="print device summary (detailsGPU analogue) and exit")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a JAX platform (cpu enables the virtual "
                        "host-device mesh for distributed modes without "
                        "TPU hardware)")
    p.add_argument("--host-device-count", type=int, default=None,
                   help="with --platform cpu: number of virtual host "
                        "devices (XLA_FLAGS --xla_force_host_platform_"
                        "device_count)")
    m = p.add_argument_group(
        "multi-host (the mpiexec launch line; on TPU pods these are "
        "discovered from the environment — pass none of them)")
    m.add_argument("--coordinator", default=None,
                   help="coordinator address host:port "
                        "(jax.distributed.initialize)")
    m.add_argument("--num-processes", type=int, default=None)
    m.add_argument("--process-id", type=int, default=None)
    m.add_argument("--multihost", action="store_true",
                   help="initialize jax.distributed from the environment "
                        "(TPU pod metadata) even with no explicit "
                        "coordinator")
    return p


def _apply_platform(args) -> None:
    """Must run before any jax backend use. The image's sitecustomize may
    force-register a TPU backend, so the env var alone is not enough — the
    live config update is what wins."""
    if args.host_device_count:
        # Affects only the host (CPU) platform; without --platform cpu this
        # just pre-sets the flag and the attached platform still wins.
        from heat2d_tpu.utils.platform import set_host_device_count
        set_host_device_count(args.host_device_count)
    if args.platform == "cpu":
        from heat2d_tpu.utils.platform import force_host_devices
        force_host_devices(args.host_device_count or 1, platform="cpu")
    elif args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        import jax
        jax.config.update("jax_platforms", args.platform)
    if args.accum_dtype == "float64":
        import jax
        jax.config.update("jax_enable_x64", True)


def _run_with_periodic_checkpoints(solver, u0, cfg, args, start_step,
                                   ckpt):
    """Drive the run in K-step segments, writing a restart point after
    each — the periodic-dump failure-recovery hook SURVEY.md §5.3/5.4
    notes the reference lacked. With convergence on, K must be a multiple
    of INTERVAL so the check schedule matches an unsegmented run; the one
    residual semantic difference left: convergence landing exactly on a
    segment boundary is only noticed one INTERVAL into the next segment.
    Reported elapsed is the sum of segment timings (host checkpoint I/O
    excluded, matching the reference's clock placement).

    ``ckpt`` is a ``resil.AsyncCheckpointer``: each restart point is
    snapshotted to host between segments and written/committed on a
    background thread while the next segment computes, so checkpoint
    I/O no longer serializes with the run even in wall-clock terms.
    Multihost stays collective-safe — the writer keeps every barrier on
    this (main) thread. The final ``flush`` (in ``close``) makes every
    snapshot durable before the CLI reports success."""
    from heat2d_tpu.models.solver import Heat2DSolver, RunResult

    k = args.checkpoint_every
    if k < 1:
        raise ConfigError(f"--checkpoint-every must be >= 1, got {k}")
    if solver.config.convergence and k % solver.config.interval:
        raise ConfigError(
            f"--checkpoint-every ({k}) must be a multiple of --interval "
            f"({solver.config.interval}) when --convergence is on, so the "
            f"residual-check schedule matches an unsegmented run")

    total = solver.config.steps
    seg_solvers = {}
    u, done, elapsed = u0, 0, 0.0
    r = None
    with ckpt:
        while done < total:
            n = min(k, total - done)
            # Warm up (untimed priming run) only the first time each
            # distinct segment length executes; repeats reuse the
            # compiled runner.
            fresh = n not in seg_solvers
            if fresh:
                seg_solvers[n] = Heat2DSolver(
                    solver.config.replace(steps=n))
            seg = seg_solvers[n]
            # gather=False: the carry stays sharded on-device across
            # segments — no cross-host allgather + re-place per K steps
            # (VERDICT r3 weak #5); the next segment consumes r.u
            # directly.
            r = seg.run(u0=u, warmup=fresh, gather=False)
            u = r.u
            done += r.steps_done
            elapsed += r.elapsed
            ckpt.save_async(u, start_step + done)
            if r.steps_done < n:  # converged early inside the segment
                break
        if r is not None:
            final_u = u
        else:  # zero remaining steps: still honor --checkpoint
            final_u = solver.run(u0=u0, timed=False, gather=False).u
            ckpt.save_async(final_u, start_step)
    return RunResult(u=final_u, steps_done=done,
                     elapsed=elapsed, config=solver.config)


def _run_ensemble_cli(args, cfg) -> int:
    """Batched (cx, cy) parameter sweep in ONE launch — the reference's
    per-configuration recompile sweeps (Report.pdf Tables 4-6) collapsed
    into a single compiled program (SURVEY.md §2.3 'DP over batch').
    Distributed modes shard members across devices on a batch mesh axis;
    serial/pallas run the whole batch on one chip."""
    import numpy as np
    import jax
    from heat2d_tpu.models.ensemble import ensemble_summary, timed_ensemble

    try:
        cxs = [float(s) for s in (args.ensemble_cx or "").split(",") if s]
        cys = [float(s) for s in (args.ensemble_cy or "").split(",") if s]
    except ValueError as e:
        print(f"bad ensemble list: {e}\nQuitting...", file=sys.stderr)
        return 1
    if not cxs or len(cxs) != len(cys):
        print("--ensemble-cx and --ensemble-cy must be non-empty, "
              "equal-length comma-separated lists\nQuitting...",
              file=sys.stderr)
        return 1
    spatial_grid = None
    if cfg.numworkers is not None:
        print(f"ensemble runs do not take --numworkers "
              f"{cfg.numworkers}: members shard over a batch mesh axis "
              f"(use --mode dist2d --gridx/--gridy for members too big "
              f"for one device)\nQuitting...", file=sys.stderr)
        return 1
    if cfg.gridx != 1 or cfg.gridy != 1:
        if cfg.mode == "dist2d":
            # Batch x spatial composition: a ('b', gridx, gridy) mesh —
            # each member spatially decomposed over its own submesh, for
            # members bigger than one device's HBM (the round-3 rejected
            # corner).
            spatial_grid = (cfg.gridx, cfg.gridy)
        else:
            # Any other mode would silently reinterpret the flags
            # (VERDICT r2 weak #3) — refuse instead.
            print(f"ensemble spatial decomposition (--gridx {cfg.gridx} "
                  f"--gridy {cfg.gridy}) is only supported with --mode "
                  f"dist2d (members run the 2D wide-halo scheme on a "
                  f"batch x spatial mesh)\nQuitting...", file=sys.stderr)
            return 1
    # Flags the ensemble path would silently ignore are rejected: a user
    # combining them must not believe they took effect. (--convergence IS
    # supported: per-member early-exit, models/ensemble.py.)
    unsupported = [flag for flag, on in [
        ("--binary-dumps", args.binary_dumps),
        ("--checkpoint", args.checkpoint is not None),
        ("--checkpoint-every", args.checkpoint_every is not None),
        ("--resume", args.resume is not None),
        ("--profile", args.profile is not None),
        # The batched runners evaluate steps AND residuals in f32; a
        # float64-accum request must not silently run as f32.
        ("--accum-dtype float64", cfg.accum_dtype == "float64")] if on]
    if unsupported:
        print(f"ensemble runs do not support {', '.join(unsupported)} "
              f"(members are dumped as final_m<i>.dat only)\nQuitting...",
              file=sys.stderr)
        return 1

    primary = jax.process_index() == 0
    sharded = cfg.mode in ("dist1d", "dist2d", "hybrid")

    registry = telemetry = None
    if args.metrics_out:
        from heat2d_tpu.obs import MetricsRegistry, TelemetryStream
        registry = MetricsRegistry()
        if cfg.convergence and not sharded and spatial_grid is None:
            # Chunk-progress streaming only where the tap is actually
            # wired (timed_ensemble nulls it on sharded/spatial meshes:
            # device-local member vectors aren't meaningful
            # cluster-wide).
            telemetry = TelemetryStream(registry=registry)
    if primary:
        print(f"Starting ensemble of {len(cxs)} members"
              + (f" over {len(jax.devices())} devices" if sharded else ""))
        print(f"Problem size:{cfg.nxprob}x{cfg.nyprob}")
        if cfg.problem != "heat5":
            print(f"Problem family: {cfg.problem}")
        if spatial_grid:
            print(f"Each member decomposed over a "
                  f"{spatial_grid[0]}x{spatial_grid[1]} spatial submesh")
        print(f"Amount of iterations: {cfg.steps}")
        if cfg.convergence:
            print(f"Check for convergence every {cfg.interval} iterations")
    try:
        batch, steps_done, elapsed = timed_ensemble(
            cfg.nxprob, cfg.nyprob, cfg.steps, cxs, cys, sharded=sharded,
            convergence=cfg.convergence, interval=cfg.interval,
            sensitivity=cfg.sensitivity, spatial_grid=spatial_grid,
            halo_depth=cfg.halo_depth, halo=cfg.halo,
            tap=(telemetry.tap_members if telemetry is not None
                 and spatial_grid is None else None),
            problem=cfg.problem)
    except (ConfigError, ValueError) as e:
        print(f"{e}\nQuitting...", file=sys.stderr)
        return 1
    # Multihost: the sharded batch spans processes — gather before any
    # host-side conversion (np.asarray on a non-addressable array raises
    # on every rank).
    from heat2d_tpu.parallel.multihost import gather_to_host
    batch = gather_to_host(batch)
    if steps_done is not None:
        steps_done = [int(s) for s in gather_to_host(steps_done)]
    if primary:
        if steps_done is not None:
            # Per-member exit report — the "Exiting after N iterations"
            # line (grad1612_mpi_heat.c:287) member-wise.
            print(f"Members exited after {steps_done} iterations")
        print(f"Elapsed time: {elapsed:e} sec")
        os.makedirs(args.outdir, exist_ok=True)
        if args.dat_layout != "none":
            from heat2d_tpu.io import (write_grid_baseline,
                                       write_grid_rowmajor)
            writer = (write_grid_baseline if args.dat_layout == "baseline"
                      else write_grid_rowmajor)
            for i, member in enumerate(batch):
                name = f"final_m{i}.dat"
                writer(member, os.path.join(args.outdir, name))
                print(f"Writing {name} ...")
        from heat2d_tpu.obs.record import build_record
        record = build_record(
            "ensemble", config=cfg, elapsed_s=elapsed,
            extra={
                "members": [
                    {"cx": cx, "cy": cy} for cx, cy in zip(cxs, cys)],
                "summary": ensemble_summary(batch,
                                            steps_done=steps_done),
            })
        if telemetry is not None and telemetry.chunk_progress():
            # Key present only when streaming actually collected chunks
            # (the 'jnp' method's vmapped loop ignores the tap) — an
            # empty list would read as 'zero chunks ran'.
            record["chunk_progress"] = telemetry.chunk_progress()
        from heat2d_tpu.tune import runtime as _tune_runtime
        tuned = _tune_runtime.applied_configs()
        if tuned:
            record["tuned_config"] = tuned
        if getattr(args, "trace_span", None) is not None:
            # run-record schema row: the request's trace — merge the
            # span files with heat2d-tpu-trace (docs/OBSERVABILITY.md)
            record["trace_id"] = args.trace_span.ctx.trace_id
        if registry is not None:
            registry.gauge("elapsed_s", float(elapsed))
            registry.gauge("members", len(cxs))
            registry.write_jsonl(
                args.metrics_out,
                extra_records=[{"event": "run_record", **record}])
        if args.run_record:
            from heat2d_tpu.io.binary import write_json_atomic
            write_json_atomic(record, args.run_record)
        if cfg.debug:
            print(json.dumps(record, indent=2))
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.log_level:
        import logging
        logging.basicConfig(
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
        logging.getLogger("heat2d_tpu").setLevel(
            getattr(logging, args.log_level.upper()))
    _apply_platform(args)

    args.trace_span = None
    if args.trace_dir:
        # HEAT2D_TRACE_DIR keeps subprocess semantics identical to the
        # fleet's (children inherit the campaign), and the root span
        # gives phase()/serve spans a parent + the record a trace_id.
        # Explicit assignment, not setdefault: an explicit --trace-dir
        # must win over a stale env var or the campaign silently
        # splits across two directories.
        os.environ["HEAT2D_TRACE_DIR"] = args.trace_dir
        from heat2d_tpu.obs import tracing
        tracing.install(tracing.Tracer(args.trace_dir, service="cli"))
        args.trace_span = tracing.begin(
            "cli.run", kind="request", mode=args.mode,
            grid=f"{args.nxprob}x{args.nyprob}", steps=args.steps)
        # phase() entries on this thread nest under the run root
        tracing.set_ambient(args.trace_span.ctx)

    multihost = (args.multihost or args.coordinator is not None
                 or args.num_processes is not None
                 or args.process_id is not None)
    if multihost:
        from heat2d_tpu.parallel.multihost import initialize_distributed
        world = initialize_distributed(
            args.coordinator, args.num_processes, args.process_id,
            force=True)
        if args.debug:
            print(f"multihost world: {world}")

    if args.device_info:
        from heat2d_tpu.utils.device import print_device_summary
        print_device_summary()
        return 0

    if args.vmem_budget is not None:
        from heat2d_tpu.ops.pallas_stencil import set_vmem_budget
        try:
            set_vmem_budget(args.vmem_budget * 1024 * 1024)
        except ConfigError as e:
            print(f"{e}\nQuitting...", file=sys.stderr)
            return 1
    elif os.environ.get("HEAT2D_VMEM_BUDGET"):
        # Validate the env override at startup: modes that never touch
        # the VMEM planner would otherwise only hit a malformed value
        # at record-building time, AFTER the whole solve ran.
        from heat2d_tpu.ops.pallas_stencil import vmem_budget_bytes
        try:
            vmem_budget_bytes()
        except ConfigError as e:
            print(f"{e}\nQuitting...", file=sys.stderr)
            return 1

    try:
        cfg = HeatConfig(
            nxprob=args.nxprob, nyprob=args.nyprob, steps=args.steps,
            cx=args.cx, cy=args.cy, gridx=args.gridx, gridy=args.gridy,
            convergence=args.convergence, interval=args.interval,
            sensitivity=args.sensitivity, mode=args.mode,
            accum_dtype=args.accum_dtype, numworkers=args.numworkers,
            strict_baseline=args.strict_baseline, debug=args.debug,
            halo_depth=args.halo_depth, halo=args.halo,
            bitwise_parity=args.bitwise_parity, method=args.method,
            problem=args.problem)
    except ConfigError as e:
        print(f"{e}\nQuitting...", file=sys.stderr)
        return 1

    if args.ensemble_cx or args.ensemble_cy:
        try:
            return _run_ensemble_cli(args, cfg)
        finally:
            if args.trace_span is not None:
                args.trace_span.end()
            if multihost:
                from heat2d_tpu.parallel.multihost import (
                    shutdown_distributed)
                shutdown_distributed()

    # Imports deferred so --help/--device-info don't pay jax startup.
    import numpy as np
    from heat2d_tpu.io import (save_checkpoint, load_checkpoint,
                               read_binary, write_binary,
                               write_binary_sharded, write_grid_baseline,
                               write_grid_rowmajor)
    from heat2d_tpu.models.solver import Heat2DSolver

    # Output and logging are rank-0's job, as in the reference (the master
    # prints and writes final.dat; rank 0 does the binary->text conversion
    # — grad1612_mpi_heat.c:66-69, 319-323).
    import jax
    primary = jax.process_index() == 0

    def say(msg):
        if primary:
            print(msg)

    from heat2d_tpu.parallel.multihost import gather_to_host as to_host

    # Startup banner (grad1612_mpi_heat.c:66-69).
    say(f"Starting with {cfg.n_shards} shards")
    say(f"Problem size:{cfg.nxprob}x{cfg.nyprob}")
    if cfg.problem != "heat5":
        say(f"Problem family: {cfg.problem}")
    if cfg.mode in ("dist2d", "hybrid"):
        say(f"Each shard will take: {cfg.xcell}x{cfg.ycell}")
    say(f"Amount of iterations: {cfg.steps}")
    if cfg.convergence:
        say(f"Check for convergence every {cfg.interval} iterations")

    # Telemetry (obs/): opt-in via --metrics-out. The registry records
    # host-side metrics (always safe); the stream wires the in-loop
    # residual tap into the compiled convergence loop (an extra
    # debug_callback per INTERVAL — without the flag the traced program
    # is byte-identical to the untelemetered one).
    registry = telemetry = None
    if args.metrics_out:
        from heat2d_tpu.obs import MetricsRegistry, TelemetryStream
        registry = MetricsRegistry()
        if cfg.convergence and not args.checkpoint_every:
            # (periodic-checkpoint segments rebuild solvers per segment
            # with segment-local step counts — their trajectories would
            # interleave; streaming stays off there.)
            telemetry = TelemetryStream(registry=registry)
        registry.event("run_start", mode=cfg.mode,
                       grid=f"{cfg.nxprob}x{cfg.nyprob}", steps=cfg.steps)

    try:
        solver = Heat2DSolver(cfg, telemetry=telemetry)
    except (ConfigError, ValueError) as e:
        print(f"{e}\nQuitting...", file=sys.stderr)
        return 1

    if cfg.debug and solver.mesh is not None:
        # DEBUG topology dump (grad1612_mpi_heat.c:170-175): one line per
        # shard with its exchange partners, -1 = no neighbor
        # (MPI_PROC_NULL at the non-periodic edges). Shape read from the
        # mesh actually built, not re-derived from the config.
        from heat2d_tpu.parallel.mesh import neighbor_table
        gx, gy = solver.mesh.devices.shape
        for row in neighbor_table(gx, gy):
            say(f"shard {row['shard']} at ({row['x']},{row['y']}): "
                f"N={row['north']} S={row['south']} "
                f"W={row['west']} E={row['east']}")

    # Managed-checkpoint mode: an existing directory (or trailing '/')
    # selects the resil.CheckpointManager — manifest, retention/GC, and
    # torn-entry fallback on resume (docs/RESILIENCE.md).
    from heat2d_tpu.io.binary import CheckpointCorruptError
    from heat2d_tpu.resil import (AsyncCheckpointer, CheckpointManager,
                                  is_manager_dir)
    ckpt_manager = None
    if args.checkpoint and (is_manager_dir(args.checkpoint)
                            or args.checkpoint.endswith(os.sep)):
        ckpt_manager = CheckpointManager(
            args.checkpoint, keep=args.checkpoint_keep or None,
            registry=registry)

    start_step = 0
    resumed = False
    if args.resume:
        try:
            if is_manager_dir(args.resume):
                # registry=None: the CLI records the restore below —
                # the manager would double-count it.
                found = CheckpointManager(
                    args.resume, keep=None).latest_valid()
                if found is None:
                    print(f"ERROR: no valid checkpoint in "
                          f"{args.resume} (every manifest entry is "
                          f"missing or torn)\nQuitting...",
                          file=sys.stderr)
                    return 1
                grid, start_step, ck_cfg = found
            else:
                grid, start_step, ck_cfg = load_checkpoint(
                    args.resume, shape=cfg.shape)
        except CheckpointCorruptError as e:
            print(f"ERROR: checkpoint failed integrity verification "
                  f"({e}); pass a checkpoint DIRECTORY to fall back to "
                  f"the previous snapshot\nQuitting...", file=sys.stderr)
            return 1
        resumed = True
        say(f"Resuming from step {start_step}")
        if registry is not None:
            registry.counter("resil_restore_total")
            registry.gauge("resil_restore_step", start_step)
        if tuple(grid.shape) != cfg.shape:
            print(f"ERROR: checkpoint grid is {grid.shape[0]}x"
                  f"{grid.shape[1]} but config is {cfg.nxprob}x"
                  f"{cfg.nyprob}\nQuitting...", file=sys.stderr)
            return 1
        remaining = max(cfg.steps - start_step, 0)
        solver = Heat2DSolver(cfg.replace(steps=remaining),
                              telemetry=telemetry)
        u0 = solver.place(grid)
    else:
        u0 = solver.init_state()

    def write_dat(u_host, name):
        if args.dat_layout == "none" or not primary:
            return
        path = os.path.join(args.outdir, name)
        if args.dat_layout == "baseline":
            write_grid_baseline(u_host, path)
        else:
            write_grid_rowmajor(u_host, path)
        print(f"Writing {name} ...")

    def dump_binary(u, name):
        """Binary dump: per-shard collective parallel write when the grid
        spans hosts (the MPI_File_write_all analogue — no process
        materializes the full grid), rank-0 write otherwise. Returns the
        path when a complete file exists on this host's filesystem."""
        path = os.path.join(args.outdir, name)
        if not getattr(u, "is_fully_addressable", True):
            write_binary_sharded(u, path, shape=cfg.shape)
            return path
        if primary:
            write_binary(
                np.asarray(u)[:cfg.nxprob, :cfg.nyprob], path)
        return path

    def grid_to_host(u, binary_path=None):
        """Full grid on this host for text output. When a per-shard
        binary was just written, rank 0 reads it back instead of
        allgathering — the reference's binary->text conversion flow
        (grad1612_mpi_heat.c:319-323); other ranks get None (they never
        write text)."""
        if (binary_path is not None
                and not getattr(u, "is_fully_addressable", True)):
            return read_binary(binary_path, cfg.shape) if primary else None
        return to_host(u)[:cfg.nxprob, :cfg.nyprob]

    try:
        os.makedirs(args.outdir, exist_ok=True)
        init_bin = None
        if args.binary_dumps:
            init_bin = dump_binary(u0, "initial_binary.dat")
        if args.dat_layout != "none":
            # Cropped to the problem domain (equal-shard padding from
            # uneven decompositions / resume re-place is stripped).
            write_dat(grid_to_host(u0, init_bin), "initial.dat")

        ckpt_writer = None
        try:
            from heat2d_tpu.utils.profiling import profile_span
            with profile_span(args.profile):
                if args.checkpoint_every:
                    if not args.checkpoint:
                        raise ConfigError(
                            "--checkpoint-every requires --checkpoint "
                            "(the path the restart points are written to)")
                    ckpt_writer = AsyncCheckpointer(
                        ckpt_manager if ckpt_manager is not None
                        else args.checkpoint,
                        cfg, shape=cfg.shape, registry=registry)
                    result = _run_with_periodic_checkpoints(
                        solver, u0, cfg, args, start_step, ckpt_writer)
                else:
                    # gather=False: output is written per-shard when it
                    # spans hosts; the global grid is only assembled (or
                    # read back from the binary) where text output needs
                    # it.
                    result = solver.run(u0=u0, gather=False)
        except ConfigError as e:
            # Includes kernel-level fast-fails (the VMEM working-set
            # check) — reported actionably instead of a traceback.
            print(f"{e}\nQuitting...", file=sys.stderr)
            return 1

        total_steps = start_step + result.steps_done
        say(f"Exiting after {result.steps_done} iterations")
        say(f"Elapsed time: {result.elapsed:e} sec")
        fin_bin = None
        if args.binary_dumps:
            fin_bin = dump_binary(result.u, "final_binary.dat")
        u_host = None
        if args.dat_layout != "none":
            u_host = grid_to_host(result.u, fin_bin)
            write_dat(u_host, "final.dat")
        if args.checkpoint and not args.checkpoint_every:
            # (the periodic path already saved the final restart point)
            if ckpt_manager is not None:
                if not getattr(result.u, "is_fully_addressable", True):
                    # collective per-shard snapshot (all ranks)
                    ckpt_manager.save(result.u, total_steps, cfg,
                                      shape=cfg.shape)
                elif primary:
                    if u_host is None:
                        u_host = grid_to_host(result.u)
                    ckpt_manager.save(u_host, total_steps, cfg)
            elif not getattr(result.u, "is_fully_addressable", True):
                # collective per-shard checkpoint write (all ranks)
                save_checkpoint(result.u, total_steps, cfg,
                                args.checkpoint, shape=cfg.shape)
            elif primary:
                if u_host is None:
                    u_host = grid_to_host(result.u)
                save_checkpoint(u_host, total_steps, cfg, args.checkpoint)

        # Unified run record (obs/record.py): to_record() carries the
        # shared envelope (schema, timestamp, device, world) + the
        # compile/warmup metric; the CLI adds its mode-specific extras.
        record = result.to_record()
        record["total_steps_including_resume"] = total_steps
        # Kernel-plan provenance (docs/TUNING.md): which source set the
        # active VMEM planning budget, and any tuned configs the opt-in
        # tuning db (HEAT2D_TUNE_DB) supplied to the band planners.
        from heat2d_tpu.ops import pallas_stencil as _ps
        record["vmem_budget"] = {
            "bytes": _ps.vmem_budget_bytes(),
            "source": _ps.vmem_budget_source()}
        from heat2d_tpu.tune import runtime as _tune_runtime
        tuned = _tune_runtime.applied_configs()
        if tuned:
            record["tuned_config"] = tuned
        if resumed:
            record["resume_from_step"] = start_step
        if ckpt_writer is not None:
            record["checkpoints_written"] = ckpt_writer.saves
        if getattr(args, "trace_span", None) is not None:
            record["trace_id"] = args.trace_span.ctx.trace_id
        if solver.mesh is not None:
            from heat2d_tpu.parallel.mesh import mesh_devices_summary
            record["mesh"] = mesh_devices_summary(solver.mesh)
        if telemetry is not None:
            # Resumed runs count engine steps from 0 (the solver is
            # rebuilt with steps=remaining) — shift the streamed steps
            # to ABSOLUTE step numbers so the trajectory lines up with
            # total_steps_including_resume.
            record["residual_trajectory"] = [
                {"step": p["step"] + start_step,
                 "residual": p["residual"]}
                for p in telemetry.trajectory()]
        if registry is not None:
            registry.gauge("steps_done", result.steps_done)
            registry.gauge("elapsed_s", result.elapsed)
            if result.warmup_s is not None:
                # Compile+warmup time — measured and KEPT now
                # (utils/timing.TimedCall), the setup cost the timed
                # span excludes.
                registry.gauge("warmup_compile_s", result.warmup_s)
            # Cluster-wide rank-max/mean/min (the mpiP table columns);
            # a collective when multi-process, so every rank calls it.
            record["metrics_aggregate"] = registry.aggregate_multihost()
            if primary:
                registry.write_jsonl(
                    args.metrics_out,
                    extra_records=[{"event": "run_record", **record}])
        if args.run_record and primary:
            from heat2d_tpu.io.binary import write_json_atomic
            write_json_atomic(record, args.run_record)
        if cfg.debug and primary:
            print(json.dumps(record, indent=2))
        return 0
    finally:
        if args.trace_span is not None:
            args.trace_span.end()
        if multihost:
            from heat2d_tpu.parallel.multihost import shutdown_distributed
            shutdown_distributed()


if __name__ == "__main__":
    sys.exit(main())
