"""Capacity model — from a measured latency/throughput surface to
"chips needed for N req/s at this SLO".

The reference's report answers sizing questions by table lookup over
19 hand-built benchmark tables; the serving analogue is a fitted
model over the sweep the load runner measures. The model is
deliberately simple and stated in the record so its assumptions are
auditable:

1. A sweep point QUALIFIES when the target kept up (achieved within
   ``keepup_margin`` of offered), shed at most ``max_shed_rate``, and
   met its SLO (p99 target + error budget, when one was given).
2. **Max sustainable throughput** = the largest qualifying offered
   rate's achieved req/s. If the TOP sweep point qualifies the system
   never saturated and the fit is flagged ``saturated: false`` — the
   capacity is a lower bound, and sizing from it is conservative.
3. **Per-unit rate** = max sustainable / serving units (fleet
   workers on CPU, chips on TPU — the target says which it counted),
   assuming the near-linear unit scaling the strong-scaling gate
   (docs/SCALING.md) holds serve-side; ``units_for(N)`` is then a
   ceiling division.

``fit_capacity`` is pure arithmetic over surface rows — no clocks, no
jax — so it is unit-testable against synthetic sweeps with known
capacity.
"""

from __future__ import annotations

import math
from typing import List, Optional

CAPACITY_MODEL = "heat2d-tpu/capacity-linear-per-unit/v1"


def _qualifies(row: dict, keepup_margin: float,
               max_shed_rate: float) -> bool:
    if row.get("offered_rps", 0.0) <= 0:
        return False
    keepup = row.get("achieved_rps", 0.0) \
        >= (1.0 - keepup_margin) * row["offered_rps"]
    shed_ok = row.get("shed_rate", 0.0) <= max_shed_rate
    slo_ok = bool(row.get("slo_ok", True))
    return keepup and shed_ok and slo_ok


def fit_capacity(rows: List[dict], units: int, *,
                 chips_per_unit: int = 1,
                 keepup_margin: float = 0.2,
                 max_shed_rate: float = 0.01) -> dict:
    """Fit the capacity model over surface ``rows`` (each carrying
    ``offered_rps`` / ``achieved_rps`` / ``shed_rate`` / ``slo_ok``).
    Returns the fit dict published into ``kind="load"`` run records
    and gate baselines.

    ``chips_per_unit``: devices behind ONE serving unit — 1 for the
    classic single-chip targets, the mesh size when the mesh engine is
    active (a mesh ``ServeTarget`` is one unit spanning N chips), so
    the fit (and ``advise``) can speak in chips, not just workers."""
    if units < 1:
        raise ValueError(f"units must be >= 1, got {units}")
    if chips_per_unit < 1:
        raise ValueError(f"chips_per_unit must be >= 1, got "
                         f"{chips_per_unit}")
    ranked = sorted(rows, key=lambda r: r.get("offered_rps", 0.0))
    qualifying = [r for r in ranked
                  if _qualifies(r, keepup_margin, max_shed_rate)]
    if qualifying:
        best = qualifying[-1]
        max_rps = best["achieved_rps"]
        saturated = best is not ranked[-1]
    else:
        max_rps, saturated = 0.0, True
    per_unit = max_rps / units
    chips = units * chips_per_unit
    return {
        "model": CAPACITY_MODEL,
        "units": int(units),
        "chips_per_unit": int(chips_per_unit),
        "chips": int(chips),
        "points": len(ranked),
        "qualifying_points": len(qualifying),
        "max_sustainable_rps": round(max_rps, 4),
        "per_unit_rps": round(per_unit, 4),
        "per_chip_rps": round(max_rps / chips, 4),
        #: False == the sweep never found the knee: capacity is a
        #: LOWER bound (every offered rate qualified)
        "saturated": bool(saturated),
        "criteria": {"keepup_margin": keepup_margin,
                     "max_shed_rate": max_shed_rate},
    }


def units_for(fit: dict, target_rps: float) -> Optional[int]:
    """Serving units needed to sustain ``target_rps`` under the
    fitted per-unit rate (the "chips for N req/s" answer). ``None``
    when the fit found no sustainable point — the model cannot size
    what it never saw succeed."""
    per_unit = fit.get("per_unit_rps", 0.0)
    if per_unit <= 0:
        return None
    return max(1, math.ceil(target_rps / per_unit))


def chips_for(fit: dict, target_rps: float) -> Optional[int]:
    """``units_for`` stated in CHIPS: units x the fit's
    ``chips_per_unit`` (1 on pre-mesh fits, so the two answers agree
    wherever both exist)."""
    units = units_for(fit, target_rps)
    if units is None:
        return None
    return units * int(fit.get("chips_per_unit", 1))


def sustainable_at(fit: dict, units: int) -> float:
    """The model's predicted sustainable req/s at ``units`` serving
    units (linear extrapolation from the fitted per-unit rate)."""
    return round(fit.get("per_unit_rps", 0.0) * units, 4)


def advise(fit: dict, observed_rps: float, current_units: int) -> dict:
    """Capacity advice for the control plane (docs/CONTROL.md): under
    sustained SLO burn, how many serving units the fitted model says
    the OBSERVED rate needs vs what is deployed. Pure arithmetic — the
    plane records the advice; acting on it (adding chips/workers) is
    an operator/orchestrator decision, deliberately outside the loop
    this repo automates. ``needed_units`` is None when the model never
    saw a sustainable point; an unsaturated fit makes the advice
    conservative (the fit is a lower bound)."""
    need = units_for(fit, observed_rps)
    cpu = int(fit.get("chips_per_unit", 1))
    return {
        "model": fit.get("model"),
        "observed_rps": round(float(observed_rps), 4),
        "current_units": int(current_units),
        "needed_units": need,
        "add_units": (None if need is None
                      else max(0, need - int(current_units))),
        # the same advice in CHIPS (mesh engines span chips_per_unit
        # devices per serving unit; 1 everywhere else, where these
        # rows equal the unit rows)
        "chips_per_unit": cpu,
        "current_chips": int(current_units) * cpu,
        "needed_chips": None if need is None else need * cpu,
        "fit_saturated": bool(fit.get("saturated", True)),
        "sustainable_at_current": sustainable_at(fit, current_units),
    }
