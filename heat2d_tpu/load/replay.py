"""Trace replay — recorded span timelines back into arrival schedules.

PR 9's tracing leaves one root span per served request (``fleet.
request`` at the router, ``serve.request`` at a standalone server)
carrying the request's compiled signature, tenant, and admission time.
That is exactly an arrival process: this module parses a trace
directory (the same ``load_dir``/``assemble`` reader ``heat2d-tpu-
trace`` merges with — factored once in obs/trace_cli.py, consumed
twice) into a ``Schedule`` the open-loop runner can fire at a live
target, preserving every inter-arrival gap so queueing behavior is
faithful to what production saw.

What replay preserves vs synthesizes:

- **preserved** — arrival times (the whole point: burst phase and gap
  structure drive queueing), compiled signatures (grid/steps/dtype/
  method — the batching, routing, and compile-cache keys), request
  kind (solve vs inverse), tenant.
- **synthesized** — the per-request payload operands the signature
  deliberately excludes (solve diffusivities; inverse observation
  values). Spans don't record payloads (they are observability
  metadata, not a data siphon), and operands don't affect queueing —
  they ride as traced operands through one compiled program. They are
  drawn from a seeded RNG so a replay is itself deterministic.

A signature string is ``str(req.signature())`` — a literal Python
tuple — so ``ast.literal_eval`` recovers it exactly; solve and
inverse signatures are distinguished by the leading ``"inverse"``
tag (serve/schema.py, diff/serving.py).
"""

from __future__ import annotations

import ast
import random
from typing import Optional

from heat2d_tpu.load.schedule import Arrival, Schedule

#: root-span names that mark one request admission. ``serve.request``
#: counts only when parentless: a fleet-served request has BOTH (the
#: worker-side serve.request nests under the router's wire span) and
#: must replay once.
ROOT_SPAN_NAMES = ("fleet.request", "serve.request")


def spec_from_signature(sig: tuple, rng: random.Random) -> tuple:
    """(kind, spec dict) for one recorded signature tuple.

    Solve signatures are ``(nx, ny, steps, dtype, method, convergence,
    interval, sensitivity)`` with an optional 9th ``problem`` element
    (the problem-registry axis: campaigns recorded before it exist as
    8-tuples and replay as problem="heat5"; current signatures carry
    the family explicitly); inverse signatures are ``("inverse", nx,
    ny, steps, target, iterations, adjoint, segment, dtype)`` — the
    layouts serve/schema.py and diff/serving.py define. Raises
    ``ValueError`` on anything else (a trace from a future schema
    should fail loudly, not replay garbage)."""
    if not isinstance(sig, tuple) or not sig:
        raise ValueError(f"not a signature tuple: {sig!r}")
    if sig[0] == "inverse":
        if len(sig) != 9:
            raise ValueError(f"malformed inverse signature: {sig!r}")
        _tag, nx, ny, steps, target, iterations, adjoint, seg, dtype = sig
        nx, ny = int(nx), int(ny)
        idx, vals = [], []
        for i in range(1, nx - 1):
            for j in range(1, ny - 1):
                if (i * ny + j) % 3 == 0:
                    idx.append(i * ny + j)
                    vals.append(round(rng.uniform(0.0, 2.0), 6))
        spec = {
            "nx": nx, "ny": ny, "steps": int(steps),
            "target": str(target), "iterations": int(iterations),
            "adjoint": str(adjoint), "dtype": str(dtype),
            "obs_indices": idx, "obs_values": vals,
        }
        if int(seg):
            spec["segment"] = int(seg)
        return "inverse", spec
    if len(sig) not in (8, 9):
        raise ValueError(f"malformed solve signature: {sig!r}")
    nx, ny, steps, dtype, method, convergence, interval, sens = sig[:8]
    # Pre-registry campaigns recorded 8-tuples: those replay as the
    # reference family (heat5, the only problem that existed).
    problem = str(sig[8]) if len(sig) == 9 else "heat5"
    spec = {
        "nx": int(nx), "ny": int(ny), "steps": int(steps),
        "dtype": str(dtype), "method": str(method),
        "convergence": bool(convergence),
        "cx": round(0.05 + 0.15 * rng.random(), 6),
        "cy": round(0.05 + 0.15 * rng.random(), 6),
    }
    if problem != "heat5":
        spec["problem"] = problem
    if convergence:
        spec["interval"] = int(interval)
        spec["sensitivity"] = float(sens)
    return "solve", spec


def _root_requests(traces: dict) -> list:
    """One (t0, signature string, tenant) row per request admission in
    a merged trace map ({trace_id: spans})."""
    rows = []
    for spans in traces.values():
        for s in spans:
            if (s.get("name") in ROOT_SPAN_NAMES
                    and not s.get("parent_id")):
                attrs = s.get("attrs") or {}
                sig = attrs.get("signature")
                if not sig:
                    continue    # e.g. cli.run roots: not serving traffic
                rows.append((float(s.get("t0", 0.0)), sig,
                             attrs.get("tenant") or "default"))
                break           # one admission per trace
    return rows


def schedule_from_trace_dir(trace_dir: str, seed: int = 0,
                            limit: Optional[int] = None) -> Schedule:
    """Parse every span file (+ flight post-mortems) under
    ``trace_dir`` into the arrival schedule the traced campaign
    actually served. ``limit`` keeps only the first N arrivals."""
    from heat2d_tpu.obs import trace_cli
    loaded = trace_cli.load_dir(trace_dir)
    traces = trace_cli.assemble(loaded["spans"])
    rows = sorted(_root_requests(traces))
    if not rows:
        raise ValueError(
            f"no request root spans found under {trace_dir!r} — was "
            "the campaign recorded with --trace-dir?")
    if limit is not None:
        rows = rows[:limit]
    t_origin = rows[0][0]
    rng = random.Random(seed)
    arrivals = []
    for t0, sig_str, tenant in rows:
        try:
            sig = ast.literal_eval(sig_str)
        except (ValueError, SyntaxError):
            raise ValueError(
                f"unparseable signature in trace: {sig_str!r}") from None
        kind, spec = spec_from_signature(sig, rng)
        arrivals.append(Arrival(t=t0 - t_origin, kind=kind, spec=spec,
                                tenant=tenant))
    return Schedule(arrivals, meta={
        "source": "replay", "trace_dir": trace_dir, "seed": int(seed),
        "spans_files": loaded["files"]})
