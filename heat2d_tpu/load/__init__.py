"""Load-generation + capacity-modeling subsystem (ROADMAP item 5;
docs/LOADGEN.md).

The serving platform's benchmark harness, rebuilt as a first-class
subsystem — the role the reference's 37-page hand-built report played
for the MPI solver:

- ``schedule`` — the ``Arrival``/``Schedule`` traffic shape both
                 producers emit and the runner consumes.
- ``replay``   — recorded span timelines (PR 9's ``spans-*.jsonl``)
                 parsed back into the arrival process production saw.
- ``synth``    — seeded deterministic workload generators: zipf
                 signature skew, MMPP bursts, diurnal envelopes,
                 tenant mixes, inverse heavy tails; named profiles.
- ``runner``   — open-loop execution against a live ``SolveServer``
                 or ``FleetServer`` with fidelity + latency +
                 throughput measurement (``load_*`` families).
- ``capacity`` — the fitted capacity model: max sustainable req/s
                 per serving unit -> units needed for N req/s.
- ``gate``     — the committed-baseline serving-regression gate
                 (``bench_serve``) CI runs on every PR.
- ``cli``      — ``heat2d-tpu-load``.
"""

from heat2d_tpu.load.capacity import fit_capacity, units_for
from heat2d_tpu.load.replay import schedule_from_trace_dir
from heat2d_tpu.load.schedule import Arrival, Schedule
from heat2d_tpu.load.synth import PROFILES, MixProfile, synthesize

__all__ = ["Arrival", "Schedule", "MixProfile", "PROFILES",
           "synthesize", "schedule_from_trace_dir", "fit_capacity",
           "units_for"]
