"""Seeded synthetic workload generators — production-shaped traffic
from a seed.

The fleet soak's load (fleet/cli.py) is a uniform closed loop: one
request shape, constant concurrency, no tenants. Real traffic is none
of those things, and the reference's 37-page benchmark report earned
its conclusions by sweeping SHAPES, not just rates. This module
generates parameterized arrival processes:

- **signature skew** — requests draw their compiled signature from a
  zipf distribution (``zipf_s`` > 0: a hot head and a long cold tail,
  the shape that stresses per-signature compile caches and rendezvous
  routing; 0 == uniform);
- **burst modulation** — an MMPP-style two-state (ON/OFF) modulated
  Poisson process: exponential dwell times per state, the ON state
  multiplying the base rate ``burst_factor``x. Inter-arrival CV > 1 —
  burstier than Poisson, the queueing regime where p99s live;
- **diurnal modulation** — a sinusoidal rate envelope (amplitude,
  period) over the burst process — the day/night cycle compressed to
  a test-sized period;
- **tenant mixes** — arrivals carry a tenant drawn from a weighted
  mix with per-tenant priority tiers (fleet targets turn these into
  ``TenantPolicy`` quotas);
- **inverse heavy tails** — a fraction of arrivals are inverse
  optimization requests whose iteration budgets draw from a Pareto
  tail (capped): the multi-second stragglers that prove the dedicated
  inverse lane and shedding actually isolate batch work.

Everything is driven by ONE ``random.Random(seed)`` consumed in a
fixed order, so a (profile, rate, duration, seed) tuple names a
workload exactly: same inputs, bit-identical ``Schedule`` (the
determinism contract ``tests/test_load.py`` pins, and what makes a
committed gate baseline meaningful).

Arrival times come from thinning: candidate gaps are drawn at the
process's peak rate and accepted with probability ``rate(t)/peak`` —
the textbook non-homogeneous Poisson construction, exact for any
bounded rate envelope.
"""

from __future__ import annotations

import bisect
import dataclasses
import math
import random
from typing import Optional, Tuple

from heat2d_tpu.load.schedule import Arrival, Schedule


def zipf_weights(n: int, s: float) -> list:
    """Normalized zipf weights over ranks 1..n: w_i ∝ (i+1)^-s.
    ``s=0`` degenerates to uniform."""
    if n < 1:
        raise ValueError(f"need n >= 1 signatures, got {n}")
    raw = [(i + 1) ** -s for i in range(n)]
    total = sum(raw)
    return [w / total for w in raw]


@dataclasses.dataclass(frozen=True)
class MixProfile:
    """One named workload shape. All knobs compose: a profile may be
    simultaneously zipf-skewed, bursty, diurnal, multi-tenant, and
    inverse-heavy (the ``production`` profile is)."""

    name: str
    #: distinct solve signatures (signature i solves ``steps + i``
    #: steps — distinct compiled programs, same grid)
    signatures: int = 4
    zipf_s: float = 0.0
    nx: int = 16
    ny: int = 16
    steps: int = 4
    method: str = "jnp"
    #: MMPP burst: ON-state rate multiplier (1.0 == modulation off)
    #: and mean exponential dwell per state
    burst_factor: float = 1.0
    burst_on_s: float = 2.0
    burst_off_s: float = 6.0
    #: diurnal sinusoid: rate *= 1 + amplitude * sin(2πt/period)
    diurnal_amplitude: float = 0.0
    diurnal_period_s: float = 30.0
    #: (tenant, weight, priority) rows; priority 0 == critical
    #: (fleet admission may use the reserved headroom)
    tenants: Tuple[tuple, ...] = (("default", 1.0, 0),)
    #: fraction of arrivals that are inverse optimization requests
    inverse_fraction: float = 0.0
    #: inverse iteration budget ~ min(cap, min * Pareto(alpha)):
    #: a heavy tail of long optimization loops
    inverse_iters_min: int = 8
    inverse_iters_cap: int = 64
    inverse_tail_alpha: float = 1.5

    def __post_init__(self):
        if self.signatures < 1:
            raise ValueError(
                f"signatures must be >= 1, got {self.signatures}")
        if self.burst_factor < 1.0:
            raise ValueError("burst_factor must be >= 1 (the ON state "
                             f"speeds traffic up), got {self.burst_factor}")
        if not (0.0 <= self.diurnal_amplitude < 1.0):
            raise ValueError("diurnal_amplitude must be in [0, 1), got "
                             f"{self.diurnal_amplitude}")
        if not (0.0 <= self.inverse_fraction <= 1.0):
            raise ValueError("inverse_fraction must be in [0, 1], got "
                             f"{self.inverse_fraction}")
        if not self.tenants:
            raise ValueError("a profile needs at least one tenant")

    def quotas(self, max_inflight: int) -> dict:
        """The fleet-side ``TenantPolicy`` map this mix implies:
        every named tenant gets its priority tier and a share of the
        global in-flight budget proportional to its weight (floored
        at 1)."""
        from heat2d_tpu.fleet.router import TenantPolicy
        total = sum(w for _n, w, _p in self.tenants)
        return {
            name: TenantPolicy(
                max_inflight=max(1, int(round(max_inflight * w / total))),
                priority=int(prio))
            for name, w, prio in self.tenants
        }


#: the named mixes the CLI exposes (--profile); ``smoke`` is the CI
#: gate's mix — small and fast but still skewed + bursty + two-tenant
PROFILES = {
    "uniform": MixProfile(name="uniform"),
    "zipf": MixProfile(name="zipf", signatures=8, zipf_s=1.1),
    "bursty": MixProfile(name="bursty", burst_factor=4.0,
                         burst_on_s=1.5, burst_off_s=4.5),
    "diurnal": MixProfile(name="diurnal", diurnal_amplitude=0.8,
                          diurnal_period_s=20.0),
    "multitenant": MixProfile(
        name="multitenant", signatures=6, zipf_s=1.1,
        tenants=(("interactive", 0.7, 0), ("batch", 0.3, 1))),
    "inverse_heavy": MixProfile(
        name="inverse_heavy", signatures=4, zipf_s=0.9,
        inverse_fraction=0.2),
    "production": MixProfile(
        name="production", signatures=8, zipf_s=1.1,
        burst_factor=3.0, burst_on_s=2.0, burst_off_s=6.0,
        diurnal_amplitude=0.5, diurnal_period_s=30.0,
        tenants=(("interactive", 0.6, 0), ("batch", 0.3, 1),
                 ("analytics", 0.1, 2)),
        inverse_fraction=0.05),
    "smoke": MixProfile(
        name="smoke", signatures=2, zipf_s=1.0, nx=12, ny=12, steps=3,
        burst_factor=2.0, burst_on_s=1.0, burst_off_s=2.0,
        tenants=(("interactive", 0.8, 0), ("batch", 0.2, 1))),
}


def _burst_toggles(rng: random.Random, profile: MixProfile,
                   duration: float) -> list:
    """ON/OFF state toggle times over [0, duration]: exponential
    dwells, starting OFF. Returns the sorted toggle instants (state
    at t = ON iff an odd number of toggles precede t)."""
    toggles, t = [], 0.0
    on = False
    while t < duration:
        mean = profile.burst_on_s if on else profile.burst_off_s
        t += rng.expovariate(1.0 / mean)
        toggles.append(t)
        on = not on
    return toggles


def _rate_factor(t: float, profile: MixProfile, toggles: list) -> float:
    """Instantaneous rate multiplier at ``t``: burst state x diurnal
    envelope (both 1.0 when the profile turns them off)."""
    f = 1.0
    if profile.burst_factor > 1.0:
        if bisect.bisect_right(toggles, t) % 2 == 1:    # ON state
            f *= profile.burst_factor
    if profile.diurnal_amplitude > 0.0:
        f *= 1.0 + profile.diurnal_amplitude * math.sin(
            2.0 * math.pi * t / profile.diurnal_period_s)
    return f


def _solve_spec(profile: MixProfile, sig_index: int,
                rng: random.Random) -> dict:
    """One solve spec on signature ``sig_index``: the signature fields
    are deterministic (grid, steps+i, method); the diffusivities vary
    per arrival (they are traced operands, not compile keys — varying
    them defeats result caches the way production payloads do) inside
    the explicit-stability box."""
    return {
        "nx": profile.nx, "ny": profile.ny,
        "steps": profile.steps + sig_index,
        "cx": round(0.05 + 0.15 * rng.random(), 6),
        "cy": round(0.05 + 0.15 * rng.random(), 6),
        "method": profile.method,
    }


def _inverse_spec(profile: MixProfile, rng: random.Random) -> dict:
    """One inverse spec with a Pareto-tailed iteration budget and a
    seeded sparse observation set (every 3rd cell of a seeded smooth
    field — identifiable, cheap, deterministic)."""
    iters = min(profile.inverse_iters_cap,
                int(profile.inverse_iters_min
                    * rng.paretovariate(profile.inverse_tail_alpha)))
    nx, ny = profile.nx, profile.ny
    idx, vals = [], []
    a = rng.uniform(0.5, 2.0)
    b = rng.uniform(0.5, 2.0)
    for i in range(1, nx - 1):
        for j in range(1, ny - 1):
            if (i * ny + j) % 3 == 0:
                idx.append(i * ny + j)
                vals.append(round(
                    a * math.sin(math.pi * i / nx)
                    * math.sin(math.pi * b * j / ny), 6))
    return {
        "nx": nx, "ny": ny, "steps": profile.steps,
        "obs_indices": idx, "obs_values": vals,
        "iterations": max(profile.inverse_iters_min, iters),
        "lr": 0.05,
    }


def synthesize(profile: MixProfile, rate: float, duration: float,
               seed: int = 0,
               max_arrivals: Optional[int] = None) -> Schedule:
    """Generate the (profile, rate, duration, seed) workload.

    ``rate`` is the BASE Poisson rate (req/s) before burst/diurnal
    modulation — the schedule's realized ``offered_rps()`` is the
    measured truth a surface row records. ``max_arrivals`` bounds
    runaway high-rate sweeps."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    if duration <= 0:
        raise ValueError(f"duration must be > 0, got {duration}")
    rng = random.Random(seed)
    toggles = (_burst_toggles(rng, profile, duration)
               if profile.burst_factor > 1.0 else [])
    peak = (rate * profile.burst_factor
            * (1.0 + profile.diurnal_amplitude))
    sig_weights = zipf_weights(profile.signatures, profile.zipf_s)
    sig_pop = list(range(profile.signatures))
    tenant_pop = [name for name, _w, _p in profile.tenants]
    tenant_weights = [w for _n, w, _p in profile.tenants]

    arrivals = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration:
            break
        # thinning: accept with prob rate(t)/peak
        if rng.random() * peak > rate * _rate_factor(t, profile,
                                                     toggles):
            continue
        tenant = rng.choices(tenant_pop, weights=tenant_weights)[0]
        if rng.random() < profile.inverse_fraction:
            arrivals.append(Arrival(
                t=t, kind="inverse",
                spec=_inverse_spec(profile, rng), tenant=tenant))
        else:
            sig = rng.choices(sig_pop, weights=sig_weights)[0]
            arrivals.append(Arrival(
                t=t, kind="solve",
                spec=_solve_spec(profile, sig, rng), tenant=tenant))
        if max_arrivals is not None and len(arrivals) >= max_arrivals:
            break
    return Schedule(arrivals, meta={
        "source": "synth", "profile": profile.name,
        "rate": float(rate), "duration_s": float(duration),
        "seed": int(seed)})
