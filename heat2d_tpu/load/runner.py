"""Open-loop load runner — fire a schedule at a live target and
measure the latency/throughput surface.

Open-loop is the discipline: arrivals fire at their SCHEDULED times
whether or not earlier requests have answered (a closed loop — the
fleet soak's semaphore — self-throttles when the server slows down,
which hides exactly the queueing collapse a capacity model must see).
The runner submits on one pacing thread, records outcomes on future
callbacks, and reports:

- **fidelity** — intended vs actual submit time per arrival
  (``load_submit_skew_s``): the proof a replayed schedule reproduced
  the recorded gaps (CI asserts the p99 bound);
- **outcomes** — ``load_requests_total{outcome}`` (completed /
  rejected_* / error), with shed (queue_full / overloaded / degraded
  / quota) and timeout classes broken out of the shed rate;
- **latency** — ``load_e2e_latency_s`` overall plus the per-signature
  ``load_signature_latency_s`` / ``load_signature_requests_total``
  families ``obs/slo.py`` evaluates (prefix="load");
- **throughput** — offered vs achieved req/s over the measured span.

Targets duck-type one protocol (``submit(request, tenant, timeout) ->
Future``): ``ServeTarget`` wraps an in-process ``SolveServer``,
``FleetTarget`` a supervised ``FleetServer`` with the mix's tenant
quotas. Tests substitute fakes — the runner never imports jax.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from heat2d_tpu.load.schedule import Schedule
from heat2d_tpu.obs.metrics import quantile
from heat2d_tpu.serve.schema import Rejected

#: rejection codes that are LOAD SHEDDING (admission said no): the
#: shed-rate numerator. Timeouts/faults are failures, not shedding;
#: invalid requests are caller bugs and count as neither.
#: ``mesh_saturated`` is the mesh engine's modeled-capacity admission
#: verdict (heat2d_tpu/mesh.MeshAdmission) — shedding by design.
SHED_CODES = ("queue_full", "overloaded", "degraded", "quota",
              "mesh_saturated")


class ServeTarget:
    """An in-process ``SolveServer`` as a load target (1 serving
    unit). ``tenant`` is accepted and ignored — single-process serving
    has no tenant plane.

    ``mesh=True`` serves through the mesh-aware engine
    (``heat2d_tpu/mesh``): still ONE serving unit, but spanning every
    attached chip — ``chips_per_unit`` then carries the mesh size into
    the capacity fit so sizing advice speaks in chips."""

    units = 1
    chips_per_unit = 1

    def __init__(self, registry=None, *, max_batch: int = 8,
                 max_delay: float = 0.005, max_queue: int = 256,
                 launch_deadline: Optional[float] = None,
                 cache_size: int = 0, mesh: bool = False):
        from heat2d_tpu.serve.server import SolveServer
        engine = None
        if mesh:
            from heat2d_tpu.mesh import MeshEnsembleEngine
            # max_batch becomes the per-chip bound under the mesh
            engine = MeshEnsembleEngine(registry=registry,
                                        max_batch_per_chip=max_batch)
            self.chips_per_unit = engine.n_devices
        self.max_batch = engine.max_batch if engine else max_batch
        # cache_size=0 by default: measured load must exercise the
        # SOLVE path; repeated payload hashes served from cache would
        # inflate the surface (the fleet soak makes the same call).
        self.server = SolveServer(
            max_batch=max_batch, max_delay=max_delay,
            max_queue=max_queue, cache_size=cache_size,
            launch_deadline=launch_deadline, registry=registry,
            engine=engine)
        self.server.start()

    def submit(self, req, tenant: str, timeout: Optional[float]):
        return self.server.submit(req, timeout=timeout)

    def close(self) -> None:
        self.server.stop(drain=True)


class FleetTarget:
    """A supervised worker fleet as a load target (``workers`` serving
    units). ``quotas`` come from the mix profile's tenant tiers;
    ``env`` reaches every worker (how a chaos campaign — e.g.
    ``HEAT2D_CHAOS_SLOW_WORKER_S`` — seeds a regression for the gate
    to catch)."""

    chips_per_unit = 1

    def __init__(self, workers: int = 2, registry=None, *,
                 quotas: Optional[dict] = None,
                 max_inflight: int = 256,
                 env: Optional[dict] = None,
                 default_timeout: Optional[float] = 30.0,
                 max_batch: int = 8, mesh: bool = False):
        from heat2d_tpu.fleet.router import FleetServer
        self.units = workers
        self.max_batch = max_batch
        # workers inherit the measuring process's platform (the CLI
        # resolved --platform into the environment) — a hardcoded cpu
        # here would silently fit a "TPU" capacity model on CPU
        platform = os.environ.get("JAX_PLATFORMS", "cpu")
        env = dict({"JAX_PLATFORMS": platform}, **(env or {}))
        if mesh:
            # every worker serves through its mesh engine
            # (fleet/worker.py's env knob). Co-hosted workers SHARE
            # the host's devices, so chips-per-unit is the host's
            # device count split across the workers (floor, min 1) —
            # a per-worker full count would double-charge the same
            # physical chips into the capacity fit
            env.setdefault("HEAT2D_MESH_SERVE", "1")
            import jax
            self.chips_per_unit = max(1, len(jax.devices()) // workers)
        self.fleet = FleetServer(
            workers=workers, registry=registry, quotas=quotas,
            max_batch=max_batch,
            max_inflight=max_inflight, cache_size=0,
            worker_cache_size=0, default_timeout=default_timeout,
            env=env)
        self.fleet.start()

    def submit(self, req, tenant: str, timeout: Optional[float]):
        return self.fleet.submit(req, tenant=tenant, timeout=timeout)

    def close(self) -> None:
        self.fleet.stop()


def _outcome_label(exc) -> str:
    if exc is None:
        return "completed"
    if isinstance(exc, Rejected):
        return "rejected_" + exc.code
    return "error"


def warm_target(target, schedule: Schedule,
                timeout: float = 120.0) -> int:
    """Compile-warm every distinct signature in the schedule before
    the measured window opens, so the surface measures steady-state
    serving, not jit compiles.

    Solve signatures walk the padded-capacity ladder (simultaneous
    bursts of 1, 2, 4, ... up to the target's ``max_batch``): the
    engine compiles one program per power-of-two batch capacity, and
    a capacity first hit MID-window would otherwise land its compile
    in the p99 (measurement hygiene, not a serving-path change — the
    fleet's own warm restarts deliberately stay narrower). Inverse
    signatures warm with a 1-iteration twin: the memoized
    value_and_grad is the compile; the iteration budget is a host
    loop. Warmup failures are tolerated — the measured window will
    surface them as what they are. Returns warm requests issued."""
    import dataclasses as dc
    seen = {}
    for a in schedule:
        req = a.build_request()
        seen.setdefault(req.signature(), (req, a.tenant, a.kind))
    max_batch = getattr(target, "max_batch", 8)
    issued = 0
    for req, tenant, kind in seen.values():
        if kind == "inverse":
            bursts = [[dc.replace(req, iterations=1)]]
        else:
            bursts, b = [], 1
            while b <= max_batch:
                # distinct diffusivities: the burst must not coalesce
                # (single-flight) into fewer members than its
                # capacity, nor cache-hit an earlier rung's member
                bursts.append([dc.replace(req, cx=0.9 + 1e-4 * i
                                          + 1e-3 * b)
                               for i in range(b)])
                b *= 2
        for burst in bursts:
            futs = [target.submit(r, tenant, timeout) for r in burst]
            issued += len(futs)
            for f in futs:
                try:
                    f.result(timeout)
                except Exception:   # noqa: BLE001 — best-effort
                    pass
    return issued


def run_schedule(schedule: Schedule, target, registry, *,
                 speedup: float = 1.0,
                 timeout: Optional[float] = 30.0,
                 warmup: bool = True,
                 drain_timeout: float = 120.0) -> dict:
    """Fire ``schedule`` (compressed ``speedup``x) at ``target``
    open-loop; block until every future answers (or the drain timeout
    passes); return one surface row (see module docstring for the
    metric families it fills in ``registry``)."""
    sched = schedule.scaled(speedup) if speedup != 1.0 else schedule
    if warmup:
        warm_target(target, sched)

    lock = threading.Lock()
    outcomes: dict = {}
    latencies_done = threading.Semaphore(0)
    skews = []
    t_last_done = [0.0]

    def on_done(fut, sig_str, t_submit) -> None:
        now = time.monotonic()
        exc = fut.exception()
        label = _outcome_label(exc)
        with lock:
            outcomes[label] = outcomes.get(label, 0) + 1
            t_last_done[0] = max(t_last_done[0], now)
        if registry is not None:
            registry.counter("load_requests_total", outcome=label)
            registry.counter("load_signature_requests_total",
                             signature=sig_str, outcome=label)
            if label == "completed":
                registry.observe("load_e2e_latency_s", now - t_submit)
                registry.observe("load_signature_latency_s",
                                 now - t_submit, signature=sig_str)
        latencies_done.release()

    t0 = time.monotonic()
    n = 0
    for a in sched:
        due = t0 + a.t
        now = time.monotonic()
        if due > now:
            time.sleep(due - now)
        req = a.build_request()
        sig_str = str(req.signature())
        t_submit = time.monotonic()
        skew = t_submit - due
        skews.append(skew)
        if registry is not None:
            registry.observe("load_submit_skew_s", skew)
        fut = target.submit(req, a.tenant, timeout)
        fut.add_done_callback(
            lambda f, s=sig_str, t=t_submit: on_done(f, s, t))
        n += 1
    t_submit_end = time.monotonic()

    deadline = time.monotonic() + drain_timeout
    answered = 0
    while answered < n:
        if not latencies_done.acquire(
                timeout=max(0.0, deadline - time.monotonic())):
            break
        answered += 1

    with lock:
        out = dict(outcomes)
        t_end = max(t_last_done[0], t_submit_end)
    completed = out.get("completed", 0)
    shed = sum(out.get("rejected_" + c, 0) for c in SHED_CODES)
    span = max(t_end - t0, 1e-9)
    skews_sorted = sorted(skews)
    row = {
        "arrivals": n,
        "answered": answered,
        "unanswered": n - answered,
        "offered_rps": round(sched.offered_rps(), 4),
        "achieved_rps": round(completed / span, 4),
        "completed": completed,
        "shed": shed,
        "shed_rate": round(shed / n, 6) if n else 0.0,
        "outcomes": out,
        "speedup": float(speedup),
        "fidelity": {
            "mean_abs_skew_s": round(
                sum(abs(s) for s in skews) / len(skews), 6)
            if skews else 0.0,
            # nearest-rank quantile — the registry's one convention
            "p99_skew_s": round(quantile(skews_sorted, 0.99), 6)
            if skews_sorted else 0.0,
            "max_skew_s": round(max(skews), 6) if skews else 0.0,
        },
    }
    if registry is not None:
        hists = registry.find_histograms("load_e2e_latency_s")
        for _k, summ in hists.items():
            row["latency"] = {q: summ[q]
                              for q in ("p50", "p90", "p99", "mean",
                                        "max", "count")}
        point = f"{row['offered_rps']:g}"
        registry.gauge("load_offered_rps", row["offered_rps"],
                       point=point)
        registry.gauge("load_achieved_rps", row["achieved_rps"],
                       point=point)
        registry.gauge("load_shed_rate", row["shed_rate"], point=point)
    return row


def measure_point(schedule: Schedule, target, *,
                  speedup: float = 1.0,
                  timeout: Optional[float] = 30.0,
                  slo_policy=None, warmup: bool = True) -> dict:
    """One sweep point with its OWN registry (per-point quantiles must
    not mix across offered rates) + an SLO evaluation over the
    per-signature families. Returns the surface row; the point
    registry rides in ``row["_registry"]`` for callers that export
    telemetry."""
    from heat2d_tpu.obs import MetricsRegistry, slo
    reg = MetricsRegistry()
    # the target records into its own registry; the runner's families
    # land here — per-point isolation either way
    row = run_schedule(schedule, target, reg, speedup=speedup,
                       timeout=timeout, warmup=warmup)
    row["slo"] = slo.evaluate(reg, prefix="load", default=slo_policy)
    row["slo_ok"] = all(r.get("ok", True) for r in row["slo"])
    row["_registry"] = reg
    return row
