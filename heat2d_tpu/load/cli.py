"""``heat2d-tpu-load`` — trace-driven load generation, latency/
throughput surfaces, capacity fitting, and the serving-regression
gate (docs/LOADGEN.md).

Modes compose left to right:

- **source** — ``--replay DIR`` (a recorded trace campaign's arrival
  process, gaps preserved) or ``--profile NAME`` (a seeded synthetic
  mix from ``load/synth.PROFILES``) at ``--rate``/``--duration`` (or
  a ``--sweep`` of rates);
- **target** — ``--target serve`` (in-process SolveServer) or
  ``--target fleet --workers N`` (supervised worker pool with the
  profile's tenant quotas);
- **measure** — each point runs open-loop, producing a surface row
  (offered/achieved req/s, latency quantiles, shed rate, SLO
  evaluation) and the capacity fit over all rows;
- **gate** — ``--gate --baseline FILE`` compares the surface+fit
  against a committed baseline and exits 1 on regression;
  ``--write-baseline FILE`` records a new one.

``--chaos-slow S`` seeds a DELIBERATE regression (fleet workers get
``HEAT2D_CHAOS_SLOW_WORKER_S``; serve targets an in-process launch-
latency campaign) — how CI proves the gate actually fires. ``--max-
skew S`` fails the run when replay fidelity (p99 intended-vs-actual
submit skew) exceeds S — the closed-loop proof a replayed schedule
reproduced the recorded gaps.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-load",
        description="load generation + capacity model + serving-"
                    "regression gate (docs/LOADGEN.md)")
    src = p.add_argument_group("traffic source")
    src.add_argument("--replay", default=None, metavar="DIR",
                     help="replay the arrival process recorded in a "
                          "--trace-dir campaign (spans-*.jsonl)")
    src.add_argument("--profile", default=None, metavar="NAME",
                     help="synthesize a named mix (load/synth.py: "
                          "uniform, zipf, bursty, diurnal, "
                          "multitenant, inverse_heavy, production, "
                          "smoke)")
    src.add_argument("--rate", type=float, default=8.0, metavar="RPS",
                     help="base arrival rate for --profile")
    src.add_argument("--sweep", default=None, metavar="R1,R2,...",
                     help="sweep offered rates (overrides --rate) to "
                          "map the latency/throughput surface")
    src.add_argument("--duration", type=float, default=5.0,
                     metavar="S", help="schedule length per point")
    src.add_argument("--seed", type=int, default=0)
    src.add_argument("--speedup", type=float, default=1.0,
                     help="compress the schedule Nx (replay at 2.0 = "
                          "twice production speed)")
    src.add_argument("--limit", type=int, default=None,
                     help="cap arrivals per point")
    tgt = p.add_argument_group("target")
    tgt.add_argument("--target", default="serve",
                     choices=["serve", "fleet"])
    tgt.add_argument("--mesh", action="store_true",
                     help="serve through the mesh-aware engine "
                          "(heat2d_tpu/mesh): --target serve gets a "
                          "MeshEnsembleEngine in-process, --target "
                          "fleet arms HEAT2D_MESH_SERVE=1 on every "
                          "worker; the capacity fit gains the "
                          "chips_per_unit dimension (docs/LOADGEN.md)")
    tgt.add_argument("--workers", type=int, default=2,
                     help="fleet worker subprocesses")
    tgt.add_argument("--max-inflight", type=int, default=256)
    tgt.add_argument("--timeout", type=float, default=30.0,
                     help="per-request deadline")
    slo = p.add_argument_group("SLO objectives (docs/OBSERVABILITY.md)")
    slo.add_argument("--slo-p99", type=float, default=None,
                     metavar="S")
    slo.add_argument("--slo-error-budget", type=float, default=0.001,
                     metavar="F")
    g = p.add_argument_group("gate (docs/LOADGEN.md)")
    g.add_argument("--baseline", default=None, metavar="JSON",
                   help="committed baseline surface to gate against")
    g.add_argument("--gate", action="store_true",
                   help="exit 1 when the measured surface regresses "
                        "past the margins vs --baseline")
    g.add_argument("--write-baseline", default=None, metavar="JSON",
                   help="record the measured surface as a baseline")
    g.add_argument("--gate-throughput-margin", type=float, default=0.3)
    g.add_argument("--gate-p99-factor", type=float, default=3.0)
    g.add_argument("--gate-p99-slack", type=float, default=0.25,
                   metavar="S")
    g.add_argument("--gate-shed-slack", type=float, default=0.05)
    g.add_argument("--gate-capacity-margin", type=float, default=0.5)
    p.add_argument("--chaos-slow", type=float, default=None,
                   metavar="S",
                   help="seed a regression: sleep S inside every "
                        "request pickup (fleet workers) / launch "
                        "(serve) — the gate must catch it")
    p.add_argument("--max-skew", type=float, default=None, metavar="S",
                   help="fail unless replay fidelity holds: p99 "
                        "|actual - intended| submit skew <= S")
    p.add_argument("--selftest", action="store_true",
                   help="seeded-determinism + in-process serving "
                        "smoke; exits nonzero on any failure")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write telemetry JSONL (load_* families + "
                        "the kind='load' run record)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="JAX platform (default cpu: the load gate is "
                        "a logic/serving gate, not a kernel bench)")
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"])
    return p


def _schedules(args) -> list:
    """[(label, Schedule)] — one per sweep point."""
    from heat2d_tpu.load import replay as replay_mod
    from heat2d_tpu.load import synth

    if args.replay:
        sched = replay_mod.schedule_from_trace_dir(
            args.replay, seed=args.seed, limit=args.limit)
        return [("replay", sched)]
    profile = synth.PROFILES.get(args.profile or "uniform")
    if profile is None:
        raise SystemExit(f"unknown --profile {args.profile!r} "
                         f"(known: {sorted(synth.PROFILES)})")
    rates = ([float(r) for r in args.sweep.split(",")]
             if args.sweep else [args.rate])
    return [(f"{r:g}rps",
             synth.synthesize(profile, r, args.duration,
                              seed=args.seed,
                              max_arrivals=args.limit))
            for r in rates]


def _drop_inverse_for_fleet(args, schedules) -> list:
    """The fleet wire carries solve specs only (fleet/wire.py): an
    inverse arrival cannot be dispatched to a worker, so fleet runs
    drop them with a visible count rather than polluting the outcome
    stats with structured rejections that measure nothing."""
    if args.target != "fleet":
        return schedules
    from heat2d_tpu.load.schedule import Schedule
    out = []
    for label, sched in schedules:
        solves = [a for a in sched if a.kind == "solve"]
        dropped = len(sched) - len(solves)
        if dropped:
            print(f"# {label}: dropped {dropped} inverse arrival(s) — "
                  "the fleet wire is solve-only (docs/LOADGEN.md)",
                  file=sys.stderr)
            sched = Schedule(solves, meta=dict(
                sched.meta, inverse_dropped=dropped))
        out.append((label, sched))
    return out


def _make_target(args, registry, profile=None):
    from heat2d_tpu.load.runner import FleetTarget, ServeTarget
    if args.target == "fleet":
        env = {}
        if args.chaos_slow:
            env["HEAT2D_CHAOS_SLOW_WORKER_S"] = str(args.chaos_slow)
        quotas = (profile.quotas(args.max_inflight)
                  if profile is not None else None)
        return FleetTarget(workers=args.workers, registry=registry,
                           quotas=quotas,
                           max_inflight=args.max_inflight, env=env,
                           default_timeout=args.timeout,
                           mesh=args.mesh)
    if args.chaos_slow:
        from heat2d_tpu.resil import chaos
        chaos.install(chaos.ChaosConfig(
            launch_latency_s=args.chaos_slow))
    return ServeTarget(registry=registry, mesh=args.mesh)


def _surface_markdown(rows: list, fit: dict) -> str:
    lines = [
        "| offered rps | achieved rps | p50 | p99 | shed | slo "
        "| skew p99 |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lat = r.get("latency") or {}
        lines.append(
            f"| {r['offered_rps']:g} | {r['achieved_rps']:g} "
            f"| {lat.get('p50', float('nan')):.4g} "
            f"| {lat.get('p99', float('nan')):.4g} "
            f"| {r['shed_rate']:.3g} "
            f"| {'ok' if r.get('slo_ok', True) else 'VIOLATED'} "
            f"| {r['fidelity']['p99_skew_s']:.4g} |")
    sat = ("saturated" if fit["saturated"]
           else "LOWER BOUND — sweep never saturated")
    lines.append(
        f"\ncapacity: {fit['max_sustainable_rps']:g} rps sustainable "
        f"over {fit['units']} unit(s) ({fit['per_unit_rps']:g} "
        f"rps/unit, {sat})")
    return "\n".join(lines)


def run_load(args, registry) -> int:
    from heat2d_tpu.load import capacity as cap_mod
    from heat2d_tpu.load import gate as gate_mod
    from heat2d_tpu.load import synth
    from heat2d_tpu.load.runner import measure_point
    from heat2d_tpu.obs.slo import SLOPolicy

    failures = []
    schedules = _drop_inverse_for_fleet(args, _schedules(args))
    profile = (synth.PROFILES.get(args.profile)
               if args.profile else None)
    policy = (SLOPolicy(latency_p99_s=args.slo_p99,
                        error_budget=args.slo_error_budget)
              if args.slo_p99 is not None else None)

    target = _make_target(args, registry, profile=profile)
    rows = []
    try:
        for label, sched in schedules:
            print(f"# point {label}: {len(sched)} arrivals over "
                  f"{sched.duration():.1f}s "
                  f"(offered {sched.offered_rps():.1f} rps"
                  + (f", speedup {args.speedup:g}x"
                     if args.speedup != 1.0 else "") + ")")
            row = measure_point(sched, target,
                                speedup=args.speedup,
                                timeout=args.timeout,
                                slo_policy=policy)
            point_reg = row.pop("_registry")
            row["label"] = label
            row["schedule"] = sched.summary()
            rows.append(row)
            if registry is not None:
                point = f"{row['offered_rps']:g}"
                registry.gauge("load_offered_rps",
                               row["offered_rps"], point=point)
                registry.gauge("load_achieved_rps",
                               row["achieved_rps"], point=point)
                registry.gauge("load_shed_rate", row["shed_rate"],
                               point=point)
                for labels, v in point_reg.find_counters(
                        "load_requests_total").items():
                    registry.counter("load_requests_total", v,
                                     point=point, **dict(labels))
            if row["unanswered"]:
                failures.append(
                    f"{label}: {row['unanswered']} request(s) never "
                    f"answered within the drain timeout")
    finally:
        target.close()
        if args.chaos_slow and args.target == "serve":
            # the in-process campaign must not outlive the run (the
            # fleet flavor dies with its worker processes)
            from heat2d_tpu.resil import chaos
            chaos.uninstall()

    units = getattr(target, "units", 1)
    fit = cap_mod.fit_capacity(
        rows, units,
        chips_per_unit=getattr(target, "chips_per_unit", 1))
    if registry is not None:
        registry.gauge("load_capacity_rps",
                       fit["max_sustainable_rps"])
        registry.gauge("load_capacity_per_unit_rps",
                       fit["per_unit_rps"])
        registry.gauge("load_capacity_per_chip_rps",
                       fit["per_chip_rps"])
    print(_surface_markdown(rows, fit))

    if args.max_skew is not None:
        for r in rows:
            skew = r["fidelity"]["p99_skew_s"]
            if skew > args.max_skew:
                failures.append(
                    f"{r['label']}: replay fidelity broke — p99 "
                    f"submit skew {skew:.4g}s > --max-skew "
                    f"{args.max_skew:g}s")

    gate_result = None
    if args.write_baseline:
        from heat2d_tpu.io.binary import write_json_atomic
        base = gate_mod.build_baseline(
            rows, fit, meta={
                "profile": args.profile, "replay": args.replay,
                "target": args.target, "workers": args.workers,
                "mesh": args.mesh,
                "seed": args.seed, "duration_s": args.duration,
                "slo_p99_s": args.slo_p99})
        write_json_atomic(base, args.write_baseline)
        print(f"# wrote baseline {args.write_baseline} "
              f"({len(base['rows'])} point(s))")
    if args.gate:
        if not args.baseline:
            failures.append("--gate needs --baseline FILE")
        else:
            try:
                with open(args.baseline) as f:
                    base = json.load(f)
            except (OSError, ValueError) as e:
                base, gate_failures = None, [
                    f"unreadable baseline {args.baseline}: {e}"]
            if base is not None:
                margins = gate_mod.GateMargins(
                    throughput_margin=args.gate_throughput_margin,
                    p99_factor=args.gate_p99_factor,
                    p99_slack_s=args.gate_p99_slack,
                    shed_slack=args.gate_shed_slack,
                    capacity_margin=args.gate_capacity_margin)
                gate_failures = gate_mod.compare(rows, fit, base,
                                                 margins)
            gate_result = {"baseline": args.baseline,
                           "passed": not gate_failures,
                           "failures": gate_failures}
            failures.extend(gate_failures)

    _write_metrics(args, registry, rows, fit, gate_result, failures)
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    print("load " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


def _write_metrics(args, registry, rows, fit, gate_result,
                   failures) -> None:
    from heat2d_tpu.obs.record import write_run_jsonl
    extra = {
        "source": ("replay" if args.replay
                   else f"profile:{args.profile or 'uniform'}"),
        "target": args.target,
        "mesh": args.mesh,
        "workers": (args.workers if args.target == "fleet" else 1),
        "speedup": args.speedup,
        "seed": args.seed,
        "surface": [{k: v for k, v in r.items() if k != "slo"}
                    for r in rows],
        "slo": [r.get("slo", []) for r in rows],
        "capacity": fit,
        "gate": gate_result,
        "chaos_slow_s": args.chaos_slow,
        "failures": list(failures),
    }
    write_run_jsonl(registry, args.metrics_out, "load", extra)


def run_selftest(args, registry) -> int:
    """Seeded determinism + an in-process serving smoke: the
    properties every other mode builds on, provable in seconds on
    CPU."""
    from heat2d_tpu.load import capacity as cap_mod
    from heat2d_tpu.load import synth
    from heat2d_tpu.load.runner import ServeTarget, measure_point
    from heat2d_tpu.load.schedule import Schedule

    failures = []
    a = synth.synthesize(synth.PROFILES["smoke"], 20.0, 2.0, seed=7)
    b = synth.synthesize(synth.PROFILES["smoke"], 20.0, 2.0, seed=7)
    c = synth.synthesize(synth.PROFILES["smoke"], 20.0, 2.0, seed=8)
    if a.fingerprint() != b.fingerprint():
        failures.append("same seed produced different schedules")
    if a.fingerprint() == c.fingerprint():
        failures.append("different seeds produced identical "
                        "schedules")
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "sched.jsonl")
        a.to_jsonl(path)
        if Schedule.from_jsonl(path).fingerprint() != a.fingerprint():
            failures.append("schedule JSONL round-trip drifted")

    target = ServeTarget(registry=registry)
    try:
        row = measure_point(a, target, timeout=60.0)
        row.pop("_registry")
    finally:
        target.close()
    if row["unanswered"]:
        failures.append(f"{row['unanswered']} selftest request(s) "
                        "unanswered")
    if row["completed"] < 1:
        failures.append("no request completed")
    fit = cap_mod.fit_capacity([row], getattr(target, "units", 1))
    if fit["max_sustainable_rps"] <= 0 and not row["shed"]:
        failures.append("capacity fit found no sustainable point on "
                        "a healthy run")
    print(f"selftest: {row['arrivals']} arrivals -> "
          f"{row['completed']} completed, achieved "
          f"{row['achieved_rps']:g} rps, fit "
          f"{fit['max_sustainable_rps']:g} rps")
    _write_metrics(args, registry, [row], fit, None, failures)
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    print("selftest " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.log_level:
        import logging
        logging.basicConfig(
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
        logging.getLogger("heat2d_tpu").setLevel(
            getattr(logging, args.log_level.upper()))
    # router/server process stays on CPU unless told otherwise (the
    # load gate measures serving logic; kernel speed has bench gates).
    # env alone does not flip an already-registered backend — the
    # post-import config update does (serve/cli.py's pattern).
    platform = (args.platform or os.environ.get("JAX_PLATFORMS")
                or "cpu")
    os.environ["JAX_PLATFORMS"] = platform
    import jax
    jax.config.update("jax_platforms", platform)

    from heat2d_tpu.obs import MetricsRegistry
    registry = MetricsRegistry()
    if args.selftest:
        return run_selftest(args, registry)
    if args.replay or args.profile or args.sweep:
        return run_load(args, registry)
    print("nothing to do: pass --selftest, --replay DIR, or "
          "--profile NAME [--sweep R1,R2,...] (docs/LOADGEN.md)",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
