"""Arrival schedules — the one traffic shape both halves of the load
subsystem speak.

A ``Schedule`` is an ordered list of ``Arrival``s: *when* a request
arrives (seconds from schedule start), *what* it is (a plain request
spec dict + the request kind that picks the schema class), and *who*
sent it (the tenant). Both producers emit exactly this shape —

- ``load/replay.py`` parses recorded span timelines (a fleet soak's
  ``spans-*.jsonl``) back into the arrival process production actually
  saw, and
- ``load/synth.py`` generates parameterized processes (zipf signature
  skew, MMPP bursts, diurnal modulation, tenant mixes, inverse-solve
  heavy tails) from a seed —

so the open-loop runner (``load/runner.py``) has ONE replay path and
the fidelity/measurement machinery never cares where traffic came
from. Everything here is host-side plain data (no jax): schedules are
hashable-by-fingerprint, JSONL-serializable (atomic commit, the R001
discipline), and cheap to build at admission-path scale.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional

SCHEDULE_SCHEMA = "heat2d-tpu/load-schedule/v1"

#: request kinds a schedule can carry (matches the serving protocol's
#: dispatch routing: plain solves and diff/'s inverse optimizations)
ARRIVAL_KINDS = ("solve", "inverse")


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One request arrival. ``t`` is seconds from schedule start;
    ``spec`` is the request's canonical spec dict (what
    ``SolveRequest.from_dict`` / ``InverseRequest.from_dict`` eat);
    ``kind`` routes to the right schema class; ``tenant`` rides to
    fleet targets (serve targets ignore it)."""

    t: float
    kind: str
    spec: dict
    tenant: str = "default"

    def build_request(self):
        """Materialize the serving-protocol request object (imports
        the schema lazily so schedule manipulation stays jax-free)."""
        if self.kind == "inverse":
            from heat2d_tpu.diff.serving import InverseRequest
            return InverseRequest.from_dict(dict(self.spec))
        from heat2d_tpu.serve.schema import SolveRequest
        return SolveRequest.from_dict(dict(self.spec))


class Schedule:
    """An arrival process: ``Arrival``s sorted by ``t``. ``meta``
    records provenance (profile name + seed, or the replayed trace
    dir) — labeling that rides into run records and baselines. The
    ``fingerprint`` covers arrivals only: two schedules are the same
    workload iff their arrivals match, whatever produced them."""

    def __init__(self, arrivals: List[Arrival],
                 meta: Optional[dict] = None):
        self.arrivals = sorted(arrivals, key=lambda a: a.t)
        self.meta = dict(meta or {})

    # -- shape ---------------------------------------------------------- #

    def __len__(self) -> int:
        return len(self.arrivals)

    def __iter__(self):
        return iter(self.arrivals)

    def duration(self) -> float:
        """Span from the first to the last arrival (0.0 when < 2)."""
        if len(self.arrivals) < 2:
            return 0.0
        return self.arrivals[-1].t - self.arrivals[0].t

    def offered_rps(self) -> float:
        """The schedule's own offered rate (arrivals per second over
        its span) — the x axis of a latency/throughput surface."""
        d = self.duration()
        return len(self.arrivals) / d if d > 0 else 0.0

    def inter_arrivals(self) -> List[float]:
        ts = [a.t for a in self.arrivals]
        return [b - a for a, b in zip(ts, ts[1:])]

    def signatures(self) -> dict:
        """{signature tuple: count} over the schedule — what the
        runner warms before the measured window."""
        out: dict = {}
        for a in self.arrivals:
            sig = a.build_request().signature()
            out[sig] = out.get(sig, 0) + 1
        return out

    def scaled(self, speedup: float) -> "Schedule":
        """The same arrival process compressed ``speedup``x (2.0 ==
        twice as fast — every inter-arrival gap halves, so offered
        load doubles while the traffic SHAPE — skew, burst phase —
        is preserved)."""
        if speedup <= 0:
            raise ValueError(f"speedup must be > 0, got {speedup}")
        return Schedule(
            [dataclasses.replace(a, t=a.t / speedup)
             for a in self.arrivals],
            meta=dict(self.meta, speedup=float(speedup)))

    # -- identity -------------------------------------------------------- #

    def fingerprint(self) -> str:
        """sha256 over the canonical arrival list — two schedules with
        equal fingerprints are the same workload bit for bit (the
        seeded-generator determinism contract tests pin)."""
        blob = json.dumps(
            [[round(a.t, 9), a.kind, a.tenant, a.spec]
             for a in self.arrivals],
            sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    # -- persistence ----------------------------------------------------- #

    def to_jsonl(self, path: str) -> None:
        """One header line + one line per arrival, committed
        atomically (tmp + fsync + os.replace — lint rule R001)."""
        from heat2d_tpu.io.binary import write_text_atomic
        lines = [json.dumps({"schema": SCHEDULE_SCHEMA,
                             "meta": self.meta,
                             "arrivals": len(self.arrivals)})]
        lines.extend(
            json.dumps({"t": a.t, "kind": a.kind, "tenant": a.tenant,
                        "spec": a.spec})
            for a in self.arrivals)
        write_text_atomic("\n".join(lines) + "\n", path)

    @classmethod
    def from_jsonl(cls, path: str) -> "Schedule":
        arrivals, meta = [], {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("schema") == SCHEDULE_SCHEMA:
                    meta = rec.get("meta", {})
                    continue
                arrivals.append(Arrival(
                    t=float(rec["t"]), kind=rec.get("kind", "solve"),
                    spec=dict(rec["spec"]),
                    tenant=rec.get("tenant", "default")))
        return cls(arrivals, meta=meta)

    def summary(self) -> dict:
        """JSON-safe shape row for run records / baselines."""
        kinds: dict = {}
        tenants: dict = {}
        for a in self.arrivals:
            kinds[a.kind] = kinds.get(a.kind, 0) + 1
            tenants[a.tenant] = tenants.get(a.tenant, 0) + 1
        return {
            "arrivals": len(self.arrivals),
            "duration_s": round(self.duration(), 6),
            "offered_rps": round(self.offered_rps(), 4),
            "kinds": kinds,
            "tenants": tenants,
            "fingerprint": self.fingerprint()[:16],
            "meta": self.meta,
        }
