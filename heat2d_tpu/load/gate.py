"""The serving-regression gate — a committed baseline surface vs the
measured one, with explicit margins.

``heat2d-tpu-load --gate --baseline FILE`` is the ``bench_serve``
gate ROADMAP items 1 and 5 ask for: every PR's measured surface is
compared point-by-point against a committed BENCH-style JSON and CI
fails on a serving regression — before production does.

Margins are deliberately explicit and generous-by-default: the gate
runs on shared CI hosts whose absolute speed varies run to run, so
each check is stated as "no worse than baseline by MORE than the
margin" rather than an absolute bound. A genuine regression (a chaos-
slowed worker, a batching bug, an accidental serial path) moves the
surface by multiples, not percentages — the margins separate noise
from signal:

- **throughput** — achieved req/s >= (1 - margin) x baseline's;
- **latency** — p99 <= baseline p99 x factor + slack (the additive
  slack absorbs the near-zero baselines small CPU solves produce,
  where a pure ratio would gate on microseconds);
- **shedding** — shed rate <= baseline + slack;
- **capacity** — fitted max sustainable req/s >= (1 - margin) x
  baseline's fit.

Rows are matched by offered rate (nearest, within 25% relative) so a
baseline sweep and a measured sweep tolerate rate jitter; a measured
point with no baseline partner (or vice versa) is itself a failure —
a gate that silently skips points is not a gate.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

BASELINE_SCHEMA = "heat2d-tpu/load-baseline/v1"

#: relative offered-rate distance within which rows pair up
MATCH_TOLERANCE = 0.25


@dataclasses.dataclass(frozen=True)
class GateMargins:
    """The explicit no-worse-than-baseline bounds (module docstring
    for rationale)."""

    throughput_margin: float = 0.3
    p99_factor: float = 3.0
    p99_slack_s: float = 0.25
    shed_slack: float = 0.05
    capacity_margin: float = 0.5


def build_baseline(rows: List[dict], fit: dict,
                   meta: Optional[dict] = None) -> dict:
    """The committed-baseline document for a measured surface: the
    per-point numbers the gate compares plus the capacity fit and
    provenance meta (profile/seed/target — a baseline must say what
    workload produced it)."""
    return {
        "schema": BASELINE_SCHEMA,
        "meta": dict(meta or {}),
        "rows": [{
            "offered_rps": r["offered_rps"],
            "achieved_rps": r["achieved_rps"],
            "p99_s": (r.get("latency") or {}).get("p99"),
            "shed_rate": r["shed_rate"],
            "slo_ok": bool(r.get("slo_ok", True)),
        } for r in rows],
        "capacity": {
            "max_sustainable_rps": fit.get("max_sustainable_rps"),
            "per_unit_rps": fit.get("per_unit_rps"),
            "units": fit.get("units"),
        },
    }


def _match(baseline_rows: list, offered: float) -> Optional[dict]:
    best, dist = None, None
    for b in baseline_rows:
        off = b.get("offered_rps", 0.0)
        if off <= 0:
            continue
        d = abs(off - offered) / off
        if dist is None or d < dist:
            best, dist = b, d
    if best is None or dist > MATCH_TOLERANCE:
        return None
    return best


def compare(rows: List[dict], fit: dict, baseline: dict,
            margins: GateMargins = GateMargins()) -> List[str]:
    """Gate the measured surface+fit against ``baseline``; returns
    the failure list (empty == pass). Every failure names the point,
    the numbers, and the bound so a red CI line is actionable."""
    failures: List[str] = []
    if baseline.get("schema") != BASELINE_SCHEMA:
        return [f"baseline schema {baseline.get('schema')!r} != "
                f"{BASELINE_SCHEMA!r} — refusing to gate against an "
                f"unknown document"]
    brows = baseline.get("rows", [])
    if not brows:
        return ["baseline has no surface rows"]
    matched = 0
    for r in rows:
        off = r.get("offered_rps", 0.0)
        b = _match(brows, off)
        if b is None:
            failures.append(
                f"measured point {off:g} rps has no baseline partner "
                f"(baseline offered rates: "
                f"{[x['offered_rps'] for x in brows]})")
            continue
        matched += 1
        floor = (1.0 - margins.throughput_margin) * b["achieved_rps"]
        if r["achieved_rps"] < floor:
            failures.append(
                f"throughput regression at {off:g} rps offered: "
                f"achieved {r['achieved_rps']:g} < {floor:g} "
                f"(baseline {b['achieved_rps']:g}, margin "
                f"{margins.throughput_margin})")
        p99 = (r.get("latency") or {}).get("p99")
        bp99 = b.get("p99_s")
        if p99 is not None and bp99 is not None:
            limit = bp99 * margins.p99_factor + margins.p99_slack_s
            if p99 > limit:
                failures.append(
                    f"latency regression at {off:g} rps offered: p99 "
                    f"{p99:.4g}s > {limit:.4g}s (baseline "
                    f"{bp99:.4g}s x {margins.p99_factor} + "
                    f"{margins.p99_slack_s}s)")
        limit = b.get("shed_rate", 0.0) + margins.shed_slack
        if r.get("shed_rate", 0.0) > limit:
            failures.append(
                f"shed-rate regression at {off:g} rps offered: "
                f"{r['shed_rate']:.4g} > {limit:.4g} (baseline "
                f"{b.get('shed_rate', 0.0):.4g} + "
                f"{margins.shed_slack})")
    if matched == 0:
        failures.append("no measured point matched any baseline "
                        "point — the gate compared nothing")
    # the reverse direction: a baseline point nothing measured is a
    # silently-shrunk sweep, not a pass
    measured_offered = [r.get("offered_rps", 0.0) for r in rows]
    for b in brows:
        off = b.get("offered_rps", 0.0)
        if off > 0 and not any(
                abs(m - off) / off <= MATCH_TOLERANCE
                for m in measured_offered):
            failures.append(
                f"baseline point {off:g} rps was never measured "
                f"(measured offered rates: {measured_offered}) — "
                f"shrink the baseline, not the sweep")
    bcap = (baseline.get("capacity") or {}).get("max_sustainable_rps")
    mcap = fit.get("max_sustainable_rps", 0.0)
    if bcap:
        floor = (1.0 - margins.capacity_margin) * bcap
        if mcap < floor:
            failures.append(
                f"capacity regression: fitted max sustainable "
                f"{mcap:g} rps < {floor:g} (baseline {bcap:g}, "
                f"margin {margins.capacity_margin})")
    return failures
