"""The framework's shared vocabularies — ONE definition each, jax-free.

Before this module the method vocabularies drifted independently:
``config.TIME_METHODS``, ``diff.vocab.METHODS``, and
``serve.schema.SUPPORTED_METHODS`` each hand-listed overlapping method
names (the R005-style drift class: three lists that must agree and
nothing checks). Every vocabulary now derives from the atoms here, and
the new PROBLEMS vocabulary (the spatial-operator axis, PR 17) is born
single-sourced.

jax-free on purpose: config validation, serving admission
(serve/schema.py), and the stability module all consume these on
host-side paths that must import without jax.
"""

from __future__ import annotations

# -- time discretization (the PR 14 axis) ------------------------------ #

#: Unconditionally stable (A-stable) time-stepping routes — they skip
#: the explicit stability box by design (ops/stability.py):
#:   adi — Crank-Nicolson ADI (Peaceman-Rachford) on batched
#:         tridiagonal Thomas solves (ops/tridiag.py)
#:   mg  — unsplit Crank-Nicolson solved per step by geometric
#:         multigrid V-cycles (ops/multigrid.py)
IMPLICIT_METHODS = ("adi", "mg")

#: Time-stepping schemes (config.method, docs/ALGORITHMS.md):
#: "explicit" is the reference's forward-Euler update.
TIME_METHODS = ("explicit",) + IMPLICIT_METHODS

# -- single-chip kernel routes (the ensemble/serve axis) ---------------- #

#: Explicit-scheme kernel routes of the batched ensemble runners:
#:   jnp    — vmapped golden model
#:   pallas — batched VMEM-resident kernel
#:   band   — temporally-blocked HBM-streaming band kernel
EXPLICIT_ROUTES = ("jnp", "pallas", "band")

#: Everything a serve request's ``method`` may name: 'auto' resolves
#: per shape, the explicit routes are kernel choices, and the implicit
#: methods are different MATH (serve/schema.py admission contract).
SERVE_METHODS = ("auto",) + EXPLICIT_ROUTES + IMPLICIT_METHODS

#: Routes the differentiable subsystem's adjoints cover
#: (diff/adjoint.py): the pallas single-instance kernel has no VJP
#: registration and mg's V-cycle recursion is not differentiated —
#: derived by EXCLUSION from the serve vocabulary so a new method
#: must be classified here, not silently drifted.
_NON_DIFFERENTIABLE = ("pallas", "mg")
DIFF_METHODS = tuple(m for m in SERVE_METHODS
                     if m not in _NON_DIFFERENTIABLE)

# -- problem families (the spatial-operator axis, PR 17) ---------------- #

#: Registered stencil/PDE families (heat2d_tpu/problems/):
#:   heat5     — the reference's 5-point constant-coefficient heat
#:               stencil (every pre-registry program, byte-identical)
#:   varcoef   — variable-coefficient (heterogeneous-material)
#:               diffusion, promoted from ops.stencil_step_var
#:   heat9     — 4th-order 9-point (wide-stencil) heat operator,
#:               halo width 2 (the Bandishti et al. generalization)
#:   advdiff   — advection-diffusion (central advection + diffusion)
#:   reactdiff — reaction-diffusion with a saturating NONLINEAR
#:               source (Michaelis-Menten kinetics, r*u/(1+u))
PROBLEMS = ("heat5", "varcoef", "heat9", "advdiff", "reactdiff")

#: The default family — the reference problem. Every entry point
#: defaults to it so pre-registry callers are untouched (jaxpr-pinned).
DEFAULT_PROBLEM = "heat5"

# -- fixed family constants (problems/base.py binds them) --------------- #

#: advdiff's dimensionless advection velocities (v * dt / dx): fixed
#: family constants today — the serve schema's two knobs stay (cx, cy)
#: — chosen inside both the CFL and cell-Reynolds boxes at the default
#: diffusivities (ops/stability.check_advdiff_stability).
ADVECTION_VELOCITY = (0.1, 0.1)

#: reactdiff's dimensionless reaction rate (r * dt) for the saturating
#: source ``r * u / (1 + u)`` — inside the explicit reaction-rate
#: bound (ops/stability.check_reactdiff_stability).
REACTION_RATE = 0.25
