"""Resilience subsystem — one failure-domain model through every layer.

The reference's durability story was a collective MPI-IO dump with no
loader, no atomicity, and no resume orchestration (grad1612_mpi_heat.c:
178-190, SURVEY.md §5.4): a crash mid-write leaves a torn restart point
and a long run dies with it. This package is the fault-tolerance layer
the north star ("serve heavy traffic ... handle as many scenarios as
you can imagine") requires, threaded through io/, models/, serve/, obs/
and the CLI:

- ``manager``  — ``CheckpointManager``: crash-consistent snapshots
                 (temp + ``os.replace`` commit, sha256-verified
                 sidecars — io/binary.py), a step->file manifest with
                 retention/GC, and ``latest_valid()`` that skips torn
                 entries.
- ``writer``   — ``AsyncCheckpointer``: double-buffered off-hot-loop
                 checkpoint writes; collectives stay on the main thread
                 (pipelined commit) so the multihost sharded path is
                 barrier-safe.
- ``chaos``    — fault injection (kill mid-checkpoint-write, fail N
                 launches, inject latency, and the fleet worker modes:
                 self-kill mid-load, heartbeat drop, slow worker)
                 driven by ``HEAT2D_CHAOS_*`` env vars or
                 ``install()``, so CI exercises REAL failure paths.
- ``retry``    — ``RetryPolicy``/``call_with_retries`` (capped
                 exponential backoff for transients), ``Watchdog``
                 (deadline -> structured timeout instead of a hang),
                 ``DegradedMode`` (consecutive-failure circuit breaker:
                 shed fresh load, keep serving the cache).

Metric families (obs/ registry; docs/RESILIENCE.md has the table):
``resil_ckpt_*`` (saves, GC, torn-skips, async write timing, pending
gauge), ``resil_restore_*`` (count + step), ``resil_chaos_injected_
total{point}``, and the serve-side ``serve_retries_total``,
``serve_watchdog_timeouts_total``, ``serve_degraded`` gauge,
``serve_degraded_shed_total``, ``serve_breaker_trips_total``.

Nothing in this package touches a traced value: with chaos disarmed and
no checkpointing requested, compiled programs are byte-identical to a
build without it (pinned by tests/test_resil.py).
"""

from heat2d_tpu.io.binary import CheckpointCorruptError
from heat2d_tpu.resil.chaos import ChaosConfig, ChaosError
from heat2d_tpu.resil.manager import CheckpointManager, is_manager_dir
from heat2d_tpu.resil.retry import (DegradedMode, RetryPolicy,
                                    TransientError, Watchdog,
                                    call_with_retries, default_transient)
from heat2d_tpu.resil.snapshot import snapshot_shards, snapshot_state
from heat2d_tpu.resil.writer import AsyncCheckpointer

__all__ = [
    "AsyncCheckpointer",
    "ChaosConfig",
    "ChaosError",
    "CheckpointCorruptError",
    "CheckpointManager",
    "DegradedMode",
    "RetryPolicy",
    "TransientError",
    "Watchdog",
    "call_with_retries",
    "default_transient",
    "is_manager_dir",
    "snapshot_shards",
    "snapshot_state",
]
