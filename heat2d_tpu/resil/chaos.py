"""Fault-injection harness — deliberate failures on demand.

The resilience subsystem's claims (crash-consistent checkpoints, retrying
serve launches, degraded-mode shedding) are only claims until a real
failure path executes. This module injects those failures on purpose:

- **kill a checkpoint mid-write** — ``HEAT2D_CHAOS_KILL_CKPT_AT=N``
  hard-kills the process (``os._exit(137)``, the SIGKILL exit code) at
  the Nth checkpoint's commit point. ``HEAT2D_CHAOS_KILL_CKPT_PHASE``
  picks the window: ``mid_write`` (default — only the temp file exists,
  the previous checkpoint must stay durable) or ``pre_meta`` (the binary
  was replaced but its sidecar was not — a torn pair the digest check
  must catch).
- **fail N launches** — ``HEAT2D_CHAOS_FAIL_LAUNCHES=N`` makes the first
  N serve-engine launches raise ``ChaosError`` (a transient the retry
  policy must absorb).
- **inject latency** — ``HEAT2D_CHAOS_LAUNCH_LATENCY_S`` /
  ``HEAT2D_CHAOS_CKPT_LATENCY_S`` sleep inside the launch / checkpoint
  write (drives watchdog-deadline and async-overlap tests).
- **kill a fleet worker mid-load** — ``HEAT2D_CHAOS_WORKER_KILL_AFTER=N``
  hard-kills the worker process (``os._exit(137)``) as it picks up its
  Nth request — the request is accepted but never answered, exactly the
  in-flight loss the fleet router's failover replay must absorb.
- **drop heartbeats** — ``HEAT2D_CHAOS_HEARTBEAT_DROP_AFTER=N`` makes a
  worker go silent after its Nth heartbeat while it keeps serving: the
  supervisor must declare it dead on heartbeat age alone (the
  gray-failure case process liveness checks miss).
- **slow worker** — ``HEAT2D_CHAOS_SLOW_WORKER_S`` sleeps inside each
  request pickup (drives latency-blip and routing-under-straggler
  tests).
- **kill storm mid-rollout** — ``HEAT2D_CHAOS_ROLLOUT_KILL_PHASE``
  names a control-plane rollout window (``canary`` | ``parity`` |
  ``observe`` | ``promote``); when the rollout reaches it, the hook
  fires the caller-supplied kill callback ONCE against
  ``HEAT2D_CHAOS_ROLLOUT_KILLS`` workers (0 = every alive worker —
  the full storm). This is how the control gate proves a tuning
  rollout interrupted at its worst moment never leaves a worker
  serving a non-validated config (docs/CONTROL.md).
- **kill a device in a live mesh** —
  ``HEAT2D_CHAOS_DEVICE_FAIL_AT=N`` raises ``DeviceLostError`` at the
  Nth mesh-batch launch attempt (1-based, counted across requeues)
  and marks device ``HEAT2D_CHAOS_DEVICE_FAIL_INDEX`` (default 0)
  DEAD for every later health probe — the device-level failure domain
  (docs/RESILIENCE.md) the mesh engine must answer with quarantine +
  shrink-and-requeue, not a crash.
- **hang a collective** — ``HEAT2D_CHAOS_HANG_COLLECTIVE=N`` stalls
  the Nth mesh-batch launch attempt on the host side for
  ``HEAT2D_CHAOS_HANG_COLLECTIVE_S`` seconds (default 2.0 — bounded,
  so the abandoned launch thread always frees itself) and marks the
  ``DEVICE_FAIL_INDEX`` device dead for probes: the wedged-ICI
  gray failure only the hung-collective watchdog (mesh/health.py)
  can bound.
- **flip a bit** — ``HEAT2D_CHAOS_FLIP_BIT=N`` tells the mesh engine
  to XOR one high exponent bit into the Nth launch attempt's HOST
  result buffer (member 0, grid center) before it is verified or
  served: silent data corruption on the readback path, the fault the
  ABFT checksum tier (ops/abft.py) exists to catch. The flip itself
  is applied by the engine — this module stays numpy- and jax-free
  (rule R004) and only answers "which launch".

Config comes from the environment (so CI can chaos a whole CLI
subprocess without code changes) or programmatically via ``install()``
(so in-process tests can scope an injection). **Zero overhead when
idle**: every hook first checks a module-level ``_enabled`` flag that is
only set by ``install()`` or the presence of ``HEAT2D_CHAOS_*`` env
vars; nothing here ever touches a traced value, so chaos cannot change
a compiled program — only the host-side orchestration around it.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

from heat2d_tpu.analysis.locks import AuditedLock

_ENV_PREFIX = "HEAT2D_CHAOS_"

#: phases of a checkpoint commit where a kill can be injected
CKPT_PHASES = ("mid_write", "pre_meta")

#: control-plane rollout windows where a kill storm can be injected
#: (heat2d_tpu/control/rollout.py announces each via rollout_point)
ROLLOUT_PHASES = ("canary", "parity", "observe", "promote")


class ChaosError(RuntimeError):
    """An injected transient failure (``resil.retry`` classifies it as
    retryable, like the real launch transients it stands in for)."""


class DeviceLostError(ChaosError):
    """An injected DEVICE failure inside a mesh launch — the stand-in
    for the ``XlaRuntimeError`` a real dead chip raises mid-collective.
    Carries the index of the device that died so the mesh engine's
    quarantine path can attribute blame without a probe sweep."""

    def __init__(self, device_index: int, message: str):
        super().__init__(message)
        self.device_index = device_index


def _flight_flush(reason: str) -> None:
    """Flush the crash flight recorder, if one is installed, before a
    hard kill. Cold path only (runs once, just before ``os._exit``);
    guarded so a broken recorder can never stop the kill — the chaos
    contract is that the process DIES."""
    try:
        from heat2d_tpu.obs import flight
        flight.crash_flush(reason)
    except BaseException:   # noqa: BLE001 — the kill must proceed
        pass


@dataclasses.dataclass
class ChaosConfig:
    """One injection campaign. All fields off by default; an explicit
    ``0`` is canonicalized to 'off' (``HEAT2D_CHAOS_X=0`` and an unset
    var arm nothing)."""

    kill_ckpt_at: Optional[int] = None      # 1-based checkpoint ordinal
    kill_ckpt_phase: str = "mid_write"
    fail_launches: int = 0                  # first N launches raise
    launch_latency_s: float = 0.0
    ckpt_latency_s: float = 0.0
    worker_kill_after: Optional[int] = None  # 1-based request ordinal
    heartbeat_drop_after: Optional[int] = None  # beats after N dropped
    slow_worker_s: float = 0.0
    rollout_kill_phase: Optional[str] = None  # rollout window to storm
    rollout_kills: int = 0                    # workers to kill (0=all)
    device_fail_at: Optional[int] = None      # 1-based mesh launch
    device_fail_index: int = 0                # which device dies/hangs
    hang_collective: Optional[int] = None     # 1-based mesh launch
    hang_collective_s: float = 2.0            # bounded hang duration
    flip_bit: Optional[int] = None            # 1-based mesh launch

    def __post_init__(self):
        if self.kill_ckpt_phase not in CKPT_PHASES:
            raise ValueError(
                f"kill_ckpt_phase must be one of {CKPT_PHASES}, got "
                f"{self.kill_ckpt_phase!r}")
        if (self.rollout_kill_phase is not None
                and self.rollout_kill_phase not in ROLLOUT_PHASES):
            raise ValueError(
                f"rollout_kill_phase must be one of {ROLLOUT_PHASES}, "
                f"got {self.rollout_kill_phase!r}")
        # 0 ordinals can never fire (counters are 1-based): canonicalize
        # to disarmed so any_active()/from_env treat them as unset.
        for f in ("kill_ckpt_at", "worker_kill_after",
                  "heartbeat_drop_after", "device_fail_at",
                  "hang_collective", "flip_bit"):
            if getattr(self, f) == 0:
                setattr(self, f, None)

    @classmethod
    def from_env(cls, env=os.environ) -> Optional["ChaosConfig"]:
        """A config iff any HEAT2D_CHAOS_* var is armed, else None.

        Parsing is STRICT: a garbage value (``FAIL_LAUNCHES=lots``)
        raises ``ValueError`` naming the variable instead of silently
        disarming — a chaos campaign that no-ops on a typo would let
        the test it drives pass vacuously, the worst failure mode a
        fault harness can have. Unset and empty mean 'off'; explicit
        ``0`` means 'off' too (see ``ChaosConfig``)."""
        def get(name, cast, default):
            v = env.get(_ENV_PREFIX + name)
            if v in (None, ""):
                return default
            try:
                return cast(v)
            except ValueError:
                raise ValueError(
                    f"{_ENV_PREFIX}{name}={v!r} is not a valid "
                    f"{cast.__name__} — refusing to run a chaos "
                    f"campaign that silently no-ops") from None

        cfg = cls(
            kill_ckpt_at=get("KILL_CKPT_AT", int, None),
            kill_ckpt_phase=get("KILL_CKPT_PHASE", str, "mid_write"),
            fail_launches=get("FAIL_LAUNCHES", int, 0),
            launch_latency_s=get("LAUNCH_LATENCY_S", float, 0.0),
            ckpt_latency_s=get("CKPT_LATENCY_S", float, 0.0),
            worker_kill_after=get("WORKER_KILL_AFTER", int, None),
            heartbeat_drop_after=get("HEARTBEAT_DROP_AFTER", int, None),
            slow_worker_s=get("SLOW_WORKER_S", float, 0.0),
            rollout_kill_phase=get("ROLLOUT_KILL_PHASE", str, None),
            rollout_kills=get("ROLLOUT_KILLS", int, 0),
            device_fail_at=get("DEVICE_FAIL_AT", int, None),
            device_fail_index=get("DEVICE_FAIL_INDEX", int, 0),
            hang_collective=get("HANG_COLLECTIVE", int, None),
            hang_collective_s=get("HANG_COLLECTIVE_S", float, 2.0),
            flip_bit=get("FLIP_BIT", int, None))
        return cfg if cfg.any_active() else None

    def any_active(self) -> bool:
        return bool(self.kill_ckpt_at is not None or self.fail_launches
                    or self.launch_latency_s or self.ckpt_latency_s
                    or self.worker_kill_after is not None
                    or self.heartbeat_drop_after is not None
                    or self.slow_worker_s
                    or self.rollout_kill_phase is not None
                    or self.device_fail_at is not None
                    or self.hang_collective is not None
                    or self.flip_bit is not None)


class _Controller:
    """Active campaign + its counters. Thread-safe: checkpoint commits
    may run on the async writer thread, launches on the scheduler
    thread."""

    def __init__(self, config: ChaosConfig, registry=None):
        self.config = config
        self.registry = registry
        self._lock = AuditedLock("resil.chaos.controller")
        self.ckpt_count = 0      # checkpoints that reached mid_write
        self.launch_count = 0
        self.launches_failed = 0
        self.worker_requests = 0     # fleet-worker request pickups
        self.heartbeats = 0          # heartbeats attempted
        self.rollout_fired = False   # the storm fires exactly once
        self.mesh_launches = 0       # mesh-batch launch attempts
        self.dead_devices: set = set()   # failed/hung device indices

    def _count(self, point: str) -> None:
        if self.registry is not None:
            self.registry.counter("resil_chaos_injected_total",
                                  point=point)

    # -- hooks --------------------------------------------------------- #

    def checkpoint_point(self, phase: str) -> None:
        cfg = self.config
        with self._lock:
            if phase == "mid_write":
                self.ckpt_count += 1
            n = self.ckpt_count
        if phase == "mid_write" and cfg.ckpt_latency_s:
            self._count("ckpt_latency")
            time.sleep(cfg.ckpt_latency_s)
        if (cfg.kill_ckpt_at is not None and n == cfg.kill_ckpt_at
                and phase == cfg.kill_ckpt_phase):
            # Hard kill: no atexit, no finally blocks — the closest a
            # test harness gets to power loss / SIGKILL preemption.
            # The flight recorder (obs/flight.py) is the ONE exception:
            # a black box that doesn't survive the crash records
            # nothing, so the kill points flush it explicitly — it
            # writes only its own sidecar'd file, never the checkpoint
            # state whose torn-write windows this kill exists to test.
            _flight_flush("chaos_kill_ckpt")
            os._exit(137)

    def launch_point(self) -> None:
        cfg = self.config
        with self._lock:
            self.launch_count += 1
            fail = self.launches_failed < cfg.fail_launches
            if fail:
                self.launches_failed += 1
                n = self.launches_failed
        if cfg.launch_latency_s:
            self._count("launch_latency")
            time.sleep(cfg.launch_latency_s)
        if fail:
            self._count("launch_failure")
            raise ChaosError(
                f"injected launch failure {n}/{cfg.fail_launches}")

    def worker_request_point(self) -> None:
        cfg = self.config
        with self._lock:
            self.worker_requests += 1
            n = self.worker_requests
        if cfg.slow_worker_s:
            self._count("slow_worker")
            time.sleep(cfg.slow_worker_s)
        if (cfg.worker_kill_after is not None
                and n == cfg.worker_kill_after):
            # Hard kill mid-pickup: the request was accepted but will
            # never be answered — the supervisor sees the death and the
            # router must replay the in-flight work to a survivor. The
            # flight recorder flushes first (checkpoint_point on why):
            # the post-mortem must contain the in-flight request's
            # spans.
            self._count("worker_kill")
            _flight_flush("chaos_worker_kill")
            os._exit(137)

    def rollout_point(self, phase: str, kill_cb=None) -> None:
        """Called by the control plane's rollout as it enters each
        window (``ROLLOUT_PHASES``). When the armed phase matches,
        ``kill_cb(n)`` fires ONCE — the caller supplies the actual
        worker-killing action (``n`` workers; 0 = all alive), keeping
        this module free of any fleet/jax dependency. Runs in the
        ROUTER process: the storm it triggers kills worker
        subprocesses, never the control plane itself."""
        cfg = self.config
        if cfg.rollout_kill_phase != phase or kill_cb is None:
            return
        with self._lock:
            if self.rollout_fired:
                return
            self.rollout_fired = True
        self._count("rollout_kill")
        kill_cb(cfg.rollout_kills)

    def heartbeat_point(self) -> bool:
        """True = send the heartbeat, False = drop it (the worker keeps
        running — a gray failure only heartbeat age can detect)."""
        cfg = self.config
        with self._lock:
            self.heartbeats += 1
            n = self.heartbeats
        if (cfg.heartbeat_drop_after is not None
                and n > cfg.heartbeat_drop_after):
            self._count("heartbeat_drop")
            return False
        return True

    def mesh_launch_point(self) -> None:
        """Called by the mesh engine at each batch-launch ATTEMPT
        (requeues count — ordinals address attempts). A hang blocks
        here for ``hang_collective_s`` (the wedged collective the
        watchdog must bound; the abandoned thread frees itself when
        the bounded sleep ends); a device failure raises
        ``DeviceLostError`` and leaves the device dead for probes."""
        cfg = self.config
        with self._lock:
            self.mesh_launches += 1
            n = self.mesh_launches
        if cfg.hang_collective is not None and n == cfg.hang_collective:
            with self._lock:
                self.dead_devices.add(cfg.device_fail_index)
            self._count("hang_collective")
            time.sleep(cfg.hang_collective_s)
        if cfg.device_fail_at is not None and n == cfg.device_fail_at:
            with self._lock:
                self.dead_devices.add(cfg.device_fail_index)
            self._count("device_fail")
            raise DeviceLostError(
                cfg.device_fail_index,
                f"injected device {cfg.device_fail_index} failure at "
                f"mesh launch {n}")

    def device_probe_point(self, index: int) -> bool:
        """True = the device answers its health probe; False = it is
        (chaos-)dead. Devices die via ``device_fail_at`` or
        ``hang_collective`` and STAY dead — quarantine must hold."""
        with self._lock:
            return index not in self.dead_devices

    def flip_bit_point(self) -> Optional[int]:
        """The exponent bit the mesh engine must XOR into this launch
        attempt's host result buffer (None = healthy). Consults the
        ATTEMPT ordinal counted by ``mesh_launch_point`` — call order
        within a launch is launch-point first, flip second."""
        cfg = self.config
        if cfg.flip_bit is None:
            return None
        with self._lock:
            armed = self.mesh_launches == cfg.flip_bit
        if not armed:
            return None
        self._count("flip_bit")
        return 30    # a high exponent bit: O(|u|)-or-worse corruption


_lock = AuditedLock("resil.chaos")
_controller: Optional[_Controller] = None
_enabled = False        # fast-path guard: False == all hooks are no-ops
_env_checked = False


def install(config: Optional[ChaosConfig], registry=None) -> None:
    """Activate a campaign programmatically (tests); ``None`` disarms."""
    global _controller, _enabled, _env_checked
    with _lock:
        _env_checked = True     # explicit install overrides env loading
        if config is None or not config.any_active():
            _controller, _enabled = None, False
        else:
            _controller = _Controller(config, registry=registry)
            _enabled = True


def uninstall() -> None:
    """Disarm and forget the campaign; env vars are re-read next hook
    (fresh processes pick their campaign up from the environment)."""
    global _controller, _enabled, _env_checked
    with _lock:
        _controller, _enabled, _env_checked = None, False, False


def controller() -> Optional[_Controller]:
    """The active controller, loading HEAT2D_CHAOS_* on first use."""
    global _controller, _enabled, _env_checked
    if not _env_checked:
        with _lock:
            if not _env_checked:
                cfg = ChaosConfig.from_env()
                if cfg is not None:
                    _controller = _Controller(cfg)
                    _enabled = True
                _env_checked = True
    return _controller


def enabled() -> bool:
    controller()
    return _enabled


# -- the hooks subsystems call (cheap no-ops when idle) ---------------- #

def checkpoint_point(phase: str) -> None:
    """Called by the checkpoint commit path at each crash window."""
    if not _enabled and _env_checked:
        return
    c = controller()
    if c is not None:
        c.checkpoint_point(phase)


def launch_point() -> None:
    """Called by the serve engine before each ensemble launch."""
    if not _enabled and _env_checked:
        return
    c = controller()
    if c is not None:
        c.launch_point()


def worker_request_point() -> None:
    """Called by a fleet worker as it picks each request off its pipe."""
    if not _enabled and _env_checked:
        return
    c = controller()
    if c is not None:
        c.worker_request_point()


def heartbeat_point() -> bool:
    """Called by a fleet worker before each heartbeat; False = drop."""
    if not _enabled and _env_checked:
        return True
    c = controller()
    if c is None:
        return True
    return c.heartbeat_point()


def rollout_point(phase: str, kill_cb=None) -> None:
    """Called by the control plane's rollout at each window boundary;
    an armed campaign fires ``kill_cb`` (the storm) once at its
    phase."""
    if not _enabled and _env_checked:
        return
    c = controller()
    if c is not None:
        c.rollout_point(phase, kill_cb)


def mesh_launch_point() -> None:
    """Called by the mesh engine at each batch-launch attempt (may
    hang or raise ``DeviceLostError`` under an armed campaign)."""
    if not _enabled and _env_checked:
        return
    c = controller()
    if c is not None:
        c.mesh_launch_point()


def device_probe_point(index: int) -> bool:
    """Called by mesh health probes; False = the device is chaos-dead."""
    if not _enabled and _env_checked:
        return True
    c = controller()
    if c is None:
        return True
    return c.device_probe_point(index)


def flip_bit_point() -> Optional[int]:
    """Bit to XOR into the current mesh launch's host result buffer
    (None = healthy). The engine applies the flip; see module doc."""
    if not _enabled and _env_checked:
        return None
    c = controller()
    if c is None:
        return None
    return c.flip_bit_point()
