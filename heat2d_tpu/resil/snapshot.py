"""In-memory state snapshots — the device->host step of a checkpoint.

Factored out of ``AsyncCheckpointer`` (which pairs these with staged
file writes and atomic commits) so other consumers can reuse the ONE
snapshot definition without the I/O half: the differentiable-solve
subsystem tracks best-so-far optimizer iterates with
``snapshot_state`` (heat2d_tpu/diff/inverse.py), and the writer's
local/collective save paths both call in here. Pure host-side copies —
nothing touches a traced value, and the returned arrays never alias
device buffers (mutating them cannot corrupt a later checkpoint).
"""

from __future__ import annotations

import numpy as np


def snapshot_state(u, shape=None, dtype=np.float32) -> np.ndarray:
    """Host-resident copy of a fully-addressable array, optionally
    cropped to ``shape`` (the equal-shard padding strip of uneven
    decompositions). The snapshot half of a local checkpoint: cheap
    (one device->host copy), no file I/O. ``dtype`` defaults to the
    checkpoint format's float32; pass ``None`` to keep the source
    dtype (the optimizer's best-iterate tracking must not truncate an
    f64 run through f32)."""
    host = np.asarray(u, dtype=dtype)
    if shape is not None and tuple(host.shape) != tuple(shape):
        host = host[tuple(slice(0, s) for s in shape)]
    # np.asarray may return a zero-copy view of a host-backed array;
    # a snapshot must own its data (the caller will keep it across
    # further device mutation / optimizer steps).
    if host.base is not None or (isinstance(u, np.ndarray)
                                 and np.shares_memory(host, u)):
        host = host.copy()
    return host


def snapshot_shards(u) -> list:
    """Per-shard host blocks of a (possibly host-spanning) jax.Array:
    ``[(row0, col0, block), ...]`` for this process's addressable
    shards, replica 0 only — the snapshot half of a collective
    checkpoint (the writer's background thread turns these into
    memmap writes at their global offsets). No collectives here: safe
    to call from any thread."""
    blocks = []
    for sh in u.addressable_shards:
        if sh.replica_id != 0:
            continue
        rs, cs = sh.index
        blocks.append((rs.start or 0, cs.start or 0,
                       np.asarray(sh.data, dtype=np.float32)))
    return blocks
