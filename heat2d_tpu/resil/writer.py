"""Async double-buffered checkpoint writer — restart points off the
hot loop.

The synchronous path serializes host checkpoint I/O between compute
segments: the device sits idle while sha256 + file write run. This
writer splits a checkpoint into its two real phases and overlaps the
expensive one with compute:

1. **Snapshot** (main thread, cheap): the device state is brought to
   host memory — ``np.asarray`` for a fully-addressable array, a
   per-shard local copy for a host-spanning one. No file I/O yet.
2. **Write + commit** (background thread): the snapshot is staged,
   digested, and atomically promoted (``io.binary``'s temp +
   ``os.replace`` protocol), then indexed into the ``CheckpointManager``
   manifest. The NEXT segment's compute overlaps this entirely.

Double-buffered: at most ONE write is in flight; ``save_async`` first
waits out the previous write (so a slow disk back-pressures to
checkpoint cadence instead of queueing unbounded snapshots), then
returns as soon as the new snapshot is captured.

**Collective safety (multihost)**: jax collectives must execute in the
same order on every rank, so the background thread NEVER runs one. For
a host-spanning array the collective pieces — pre-sizing the shared
staging file and the all-ranks-done barrier before rank 0 commits — run
on the MAIN thread inside ``save_async``/``flush``; only the rank-local
block writes ride the background thread. The commit of checkpoint N is
therefore deferred to the next ``save_async`` (or ``flush``): the
pipelined-commit pattern — checkpoint N becomes durable while N+1
computes, and the manifest only ever indexes fully-barriered files.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

import numpy as np

from heat2d_tpu.analysis.locks import AuditedLock, guarded_by
from heat2d_tpu.io.binary import (checkpoint_tmp_path,
                                  commit_checkpoint_files, write_binary)
from heat2d_tpu.resil.manager import CheckpointManager
from heat2d_tpu.resil.snapshot import snapshot_shards, snapshot_state

log = logging.getLogger("heat2d_tpu.resil")


@dataclasses.dataclass
class _PendingCommit:
    """A collective checkpoint whose local writes are in flight; the
    commit (barrier + rank-0 promote + manifest) is still owed."""
    step: int
    tmp: str
    path: str
    config: object
    out_shape: tuple


@guarded_by("_lock", "_future", "_pending", "_closed", "saves")
class AsyncCheckpointer:
    """Write restart points without blocking the run.

    ``target`` is a ``CheckpointManager`` (directory mode: manifest,
    retention, ``latest_valid``) or a plain path (single-file restart
    point, overwritten atomically each save).
    """

    def __init__(self, target, config, shape=None, registry=None):
        self.manager = target if isinstance(target, CheckpointManager) \
            else None
        self.path = None if self.manager is not None else str(target)
        self.config = config
        self.shape = tuple(shape) if shape is not None else None
        self.registry = registry
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="heat2d-ckpt")
        self._future: Optional[Future] = None
        self._pending: Optional[_PendingCommit] = None
        self._closed = False
        self._lock = AuditedLock("resil.writer")
        self.saves = 0

    # -- public -------------------------------------------------------- #

    def save_async(self, u, step: int) -> None:
        """Snapshot ``u`` and schedule its durable commit. Returns once
        the snapshot is host-resident — file I/O overlaps the caller's
        next segment. COLLECTIVE when ``u`` spans processes (all ranks
        call, same order); a fully-addressable array is written by
        rank 0 only and the call is a no-op elsewhere."""
        with self._lock:
            if self._closed:
                raise RuntimeError("AsyncCheckpointer is closed")
            self._finish_pending_locked()
            collective = not getattr(u, "is_fully_addressable", True)
            if collective:
                self._save_collective_locked(u, step)
            else:
                self._save_local_locked(u, step)
            self.saves += 1
            self._gauge_pending()

    def flush(self) -> None:
        """Wait until every scheduled checkpoint is durable (commit
        barriers included). COLLECTIVE under multihost, like the saves
        it drains. Write errors surface here (and on the next
        ``save_async``), never silently."""
        with self._lock:
            self._finish_pending_locked()
            self._gauge_pending()

    def close(self) -> None:
        try:
            self.flush()
        finally:
            with self._lock:    # save_async reads _closed under it
                self._closed = True
            self._pool.shutdown(wait=True)

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- local (fully-addressable) path -------------------------------- #

    def _save_local_locked(self, u, step: int) -> None:
        import jax
        if jax.process_index() != 0:
            return
        host = snapshot_state(u, shape=self.shape)
        path = self._path_for(step)
        self._future = self._pool.submit(
            self._write_and_commit, host, step, path)

    def _write_and_commit(self, host, step, path) -> None:
        timer = (self.registry.timer("resil_ckpt_async_write_s")
                 if self.registry is not None else contextlib.nullcontext())
        with timer:
            tmp = checkpoint_tmp_path(path)
            write_binary(host, tmp)
            commit_checkpoint_files(tmp, path, step, self.config,
                                    host.shape)
            if self.manager is not None:
                self.manager.index(step)
            elif self.registry is not None:
                self.registry.counter("resil_ckpt_saves_total")
        log.debug("async checkpoint committed: step=%d path=%s",
                  step, path)

    # -- collective (host-spanning) path ------------------------------- #

    def _save_collective_locked(self, u, step: int) -> None:
        import jax

        path = self._path_for(step)
        tmp = checkpoint_tmp_path(path)
        nx, ny = self.shape if self.shape is not None else u.shape
        # MAIN-THREAD collective prologue: rank 0 sizes the shared
        # staging file; the barrier orders it before any rank's writes.
        if jax.process_index() == 0:
            with open(tmp, "wb") as f:
                f.truncate(nx * ny * 4)
        self._barrier(f"async-ckpt:create:{tmp}")
        # Rank-local snapshot (device->host copy, no collective).
        blocks = snapshot_shards(u)
        self._future = self._pool.submit(
            self._write_blocks, tmp, blocks, nx, ny)
        self._pending = _PendingCommit(
            step=step, tmp=tmp, path=path, config=self.config,
            out_shape=(nx, ny))

    def _write_blocks(self, tmp, blocks, nx, ny) -> None:
        timer = (self.registry.timer("resil_ckpt_async_write_s")
                 if self.registry is not None else contextlib.nullcontext())
        with timer:
            mm = np.memmap(tmp, dtype=np.float32, mode="r+",
                           shape=(nx, ny))
            try:
                for r0, c0, blk in blocks:
                    if r0 >= nx or c0 >= ny:
                        continue          # shard wholly in the padding
                    r1 = min(r0 + blk.shape[0], nx)
                    c1 = min(c0 + blk.shape[1], ny)
                    mm[r0:r1, c0:c1] = blk[:r1 - r0, :c1 - c0]
                mm.flush()
            finally:
                del mm

    # -- shared internals ---------------------------------------------- #

    def _finish_pending_locked(self) -> None:
        if self._future is not None:
            try:
                self._future.result()
            except BaseException:
                # The block write never finished: its staged tmp file
                # must NOT be committed — a later flush()/close() that
                # promoted it would digest the PARTIAL data into a
                # "verified" sidecar. Abandon the pending commit; the
                # previous checkpoint stays the durable restart point.
                self._pending = None
                raise
            finally:
                self._future = None
        if self._pending is not None:
            import jax
            p, self._pending = self._pending, None
            # MAIN-THREAD collective epilogue: every rank's blocks are
            # on disk before rank 0 promotes and indexes the pair.
            self._barrier(f"async-ckpt:done:{p.tmp}")
            if jax.process_index() == 0:
                commit_checkpoint_files(p.tmp, p.path, p.step, p.config,
                                        p.out_shape)
                if self.manager is not None:
                    self.manager.index(p.step)
                elif self.registry is not None:
                    self.registry.counter("resil_ckpt_saves_total")
            self._barrier(f"async-ckpt:committed:{p.tmp}")

    def _path_for(self, step: int) -> str:
        if self.manager is not None:
            return self.manager.path_for(step)
        return self.path

    @staticmethod
    def _barrier(name: str) -> None:
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(name)

    def _gauge_pending(self) -> None:
        if self.registry is not None:
            pending = int(self._future is not None
                          or self._pending is not None)
            self.registry.gauge("resil_ckpt_pending", pending)
