"""CheckpointManager — a directory of crash-consistent restart points.

One manager owns one directory:

```
ckpts/
  MANIFEST.json               # step -> file, newest last (atomic replace)
  ckpt_00000040.bin           # raw f32 grid (MPI-IO byte format)
  ckpt_00000040.bin.meta.json # step/shape/config + sha256 of the binary
  ckpt_00000080.bin
  ...
```

Every snapshot goes through ``io.binary.save_checkpoint``'s staged
commit (temp + ``os.replace``, digest in the sidecar), then the manifest
is rewritten atomically and snapshots beyond the retention window are
garbage-collected. ``latest_valid()`` walks the manifest newest-first,
skipping any entry that fails to load verified (torn pair, truncated
binary, missing files) — the fallback that turns "a crash mid-write"
into "resume from the previous snapshot" instead of a dead run.

Multihost: ``save`` is COLLECTIVE when the array spans processes (the
per-shard write path needs every rank); manifest/GC bookkeeping is
rank 0's, bracketed by a closing barrier so no rank resumes against a
manifest that is still being written. ``latest_valid`` is host-local
(reads the shared filesystem).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
from typing import Optional

from heat2d_tpu.io.binary import (CheckpointCorruptError, load_checkpoint,
                                  save_checkpoint)

log = logging.getLogger("heat2d_tpu.resil")

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_FORMAT = "heat2d-tpu-checkpoint-manifest-v1"
_CKPT_RE = re.compile(r"^ckpt_(\d{8})\.bin$")


def is_manager_dir(path) -> bool:
    """True when ``path`` names a checkpoint DIRECTORY (existing dir, or
    a manifest already inside it) rather than a single checkpoint file —
    how the CLI decides which resume/checkpoint flavor a path means."""
    p = str(path)
    return os.path.isdir(p) or os.path.exists(
        os.path.join(p, MANIFEST_NAME))


class CheckpointManager:
    """Retention + manifest + torn-entry fallback over atomic snapshots.

    ``keep``: number of newest snapshots retained (None/0 = keep all).
    """

    def __init__(self, directory, keep: Optional[int] = 3, registry=None):
        if keep is not None and keep < 0:
            raise ValueError(f"keep must be >= 0 or None, got {keep}")
        self.directory = str(directory)
        self.keep = keep or None
        self.registry = registry
        os.makedirs(self.directory, exist_ok=True)

    # -- paths --------------------------------------------------------- #

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{int(step):08d}.bin")

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    # -- manifest ------------------------------------------------------ #

    def manifest(self) -> list:
        """Entries as recorded, oldest first: ``[{"step", "file"}, ...]``.
        A missing/corrupt manifest degrades to a directory scan (the
        manifest is an index, not the source of truth — the verified
        sidecars are)."""
        try:
            with open(self.manifest_path) as f:
                m = json.load(f)
            entries = sorted(m["entries"], key=lambda e: int(e["step"]))
            return [{"step": int(e["step"]), "file": str(e["file"])}
                    for e in entries]
        except (OSError, ValueError, KeyError, TypeError):
            return self._scan()

    def _scan(self) -> list:
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                out.append({"step": int(m.group(1)), "file": name})
        return sorted(out, key=lambda e: e["step"])

    def steps(self) -> list:
        return [e["step"] for e in self.manifest()]

    def _write_manifest(self, entries) -> None:
        from heat2d_tpu.io.binary import _fsync_path
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"format": MANIFEST_FORMAT,
                       "entries": entries}, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)
        # directory fsync: the rename must survive power loss, like the
        # checkpoint pair it indexes (io.binary.commit_checkpoint_files)
        _fsync_path(self.directory)

    # -- save ---------------------------------------------------------- #

    def save(self, u, step: int, config, shape=None) -> str:
        """Snapshot ``u`` at ``step`` (atomic commit), index it, GC the
        retention overflow. Returns the checkpoint path. COLLECTIVE when
        ``u`` spans processes — every rank must call."""
        path = self.path_for(step)
        collective = not getattr(u, "is_fully_addressable", True)
        timer = (self.registry.timer("resil_ckpt_save_s")
                 if self.registry is not None else contextlib.nullcontext())
        with timer:
            save_checkpoint(u, step, config, path, shape=shape)
            self.index(step)
        if collective:
            import jax
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices(
                    f"ckpt-manager:save:{path}")
        return path

    def index(self, step: int) -> None:
        """Record a committed snapshot in the manifest and apply the
        retention policy (rank 0 only — a no-op elsewhere)."""
        if not self._primary():
            return
        entries = [e for e in self.manifest() if e["step"] != int(step)]
        entries.append({"step": int(step),
                        "file": os.path.basename(self.path_for(step))})
        entries.sort(key=lambda e: e["step"])
        pruned = []
        if self.keep is not None and len(entries) > self.keep:
            pruned, entries = (entries[:-self.keep], entries[-self.keep:])
        self._write_manifest(entries)
        for e in pruned:
            self._unlink(os.path.join(self.directory, e["file"]))
        if self.registry is not None:
            self.registry.counter("resil_ckpt_saves_total")
            if pruned:
                self.registry.counter("resil_ckpt_gc_total", len(pruned))
            self.registry.gauge("resil_ckpt_retained", len(entries))
            self.registry.gauge("resil_ckpt_latest_step",
                                entries[-1]["step"])

    @staticmethod
    def _primary() -> bool:
        import jax
        return jax.process_index() == 0

    def _unlink(self, path) -> None:
        for p in (path, str(path) + ".meta.json",
                  str(path) + ".tmp"):
            try:
                os.remove(p)
            except OSError:
                pass

    # -- restore ------------------------------------------------------- #

    def latest_valid(self, shape=None):
        """The newest checkpoint that LOADS VERIFIED, as
        ``(grid, step, config_dict)`` — or ``None`` when no entry
        survives. Torn/corrupt/missing entries are skipped (counted as
        ``resil_ckpt_skipped_torn_total``) and the walk falls back to
        the previous snapshot, so one crash mid-write never strands a
        resumable run."""
        for entry in reversed(self.manifest()):
            path = os.path.join(self.directory, entry["file"])
            try:
                grid, step, cfg = load_checkpoint(path, shape=shape)
            except (CheckpointCorruptError, OSError, ValueError) as e:
                log.warning("skipping torn checkpoint %s: %s", path, e)
                if self.registry is not None:
                    self.registry.counter(
                        "resil_ckpt_skipped_torn_total")
                continue
            if self.registry is not None:
                self.registry.counter("resil_restore_total")
                self.registry.gauge("resil_restore_step", step)
            return grid, step, cfg
        return None

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None
