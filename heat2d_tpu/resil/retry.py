"""Retry, watchdog, and degraded-mode policies — transient failures
absorbed, hangs bounded, overload shed.

Three small host-side mechanisms the serving layer (and any driver)
composes:

- ``RetryPolicy`` + ``call_with_retries`` — capped exponential backoff
  for TRANSIENT failures (injected ``ChaosError``, runtime/IO errors).
  Structured admission decisions (``serve.schema.Rejected``) and
  programming errors are never retried: a rejection is an answer, not a
  fault.
- ``Watchdog`` — a deadline on a block of work; on expiry it fires a
  callback (the server converts in-flight futures into structured
  ``Rejected("watchdog_timeout")``) instead of letting callers hang on
  a wedged launch.
- ``DegradedMode`` — a consecutive-failure circuit breaker: after
  ``threshold`` failures it OPENS for ``cooldown`` seconds, during
  which fresh work is shed at admission (the content-addressed cache
  keeps answering warm signatures — partial availability instead of a
  pile-up). After the cooldown one probe is admitted (HALF-OPEN); its
  success closes the breaker, its failure re-opens it.

Backoff is deterministic by default (reproducibility is a project
invariant); fleet callers opt into FULL JITTER (``jitter=True`` —
attempt i sleeps uniform(0, cap_i)) so N workers restarted by the same
failure don't thundering-herd the same signature, and tests pin the
jittered schedule through the deterministic ``rng`` seed hook.
Everything is registry-instrumented but registry-optional.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Callable, Optional

from heat2d_tpu.analysis.locks import AuditedLock
from heat2d_tpu.resil.chaos import ChaosError

log = logging.getLogger("heat2d_tpu.resil")


class TransientError(RuntimeError):
    """Marker for failures a caller knows to be retry-safe."""


def default_transient(exc: BaseException) -> bool:
    """Conservative transience classification: injected chaos, explicit
    transients, OS/IO errors, and accelerator-runtime failures (matched
    by class name — ``XlaRuntimeError``/``JaxRuntimeError`` move between
    modules across jax versions). Rejections, config and programming
    errors are terminal."""
    if isinstance(exc, (ChaosError, TransientError, OSError,
                        TimeoutError)):
        return True
    name = type(exc).__name__
    return name in ("XlaRuntimeError", "JaxRuntimeError")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff: attempt i (0-based re-try index)
    sleeps ``cap_i = min(base_delay * backoff**i, max_delay)``.

    With ``jitter=True`` the sleep is FULL-JITTERED — drawn uniform
    over ``[0, cap_i)`` — which decorrelates N processes retrying the
    same failure (the fleet supervisor's restart storm). The draw comes
    from ``rng`` (a ``random.Random``; tests seed it for a pinned
    schedule) or the module default."""

    max_attempts: int = 3       # total tries, including the first
    base_delay: float = 0.05
    backoff: float = 2.0
    max_delay: float = 2.0
    jitter: bool = False

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")

    def cap(self, retry_index: int) -> float:
        """The deterministic ceiling of attempt ``retry_index``'s sleep
        (== the sleep itself when jitter is off). A long-lived caller
        (the fleet supervisor's crash-loop restarts) can reach attempt
        indices where ``backoff ** i`` overflows a float — the cap wins
        there, it must not raise."""
        try:
            d = self.base_delay * self.backoff ** retry_index
        except OverflowError:
            return self.max_delay
        return min(d, self.max_delay)

    def delay(self, retry_index: int,
              rng: Optional[random.Random] = None) -> float:
        d = self.cap(retry_index)
        if not self.jitter:
            return d
        return d * (rng if rng is not None else random).random()


def call_with_retries(fn: Callable, policy: RetryPolicy, *,
                      classify: Callable[[BaseException], bool] = None,
                      on_retry: Callable[[int, BaseException], None] = None,
                      sleep: Callable[[float], None] = time.sleep,
                      rng: Optional[random.Random] = None):
    """Run ``fn()`` under ``policy``. Non-transient failures (per
    ``classify``, default ``default_transient``) propagate immediately;
    transients retry with backoff until attempts run out, then the LAST
    failure propagates. ``on_retry(retry_index, exc)`` fires before each
    backoff sleep (metrics hook). ``rng`` is the jitter source for
    ``jitter=True`` policies (seed it for deterministic tests)."""
    classify = default_transient if classify is None else classify
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            last_try = attempt == policy.max_attempts - 1
            if last_try or not classify(e):
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            d = policy.delay(attempt, rng=rng)
            log.warning("transient failure (attempt %d/%d), retrying "
                        "in %.3fs: %r", attempt + 1,
                        policy.max_attempts, d, e)
            sleep(d)
    raise AssertionError("unreachable")  # loop always returns or raises


class Watchdog:
    """Deadline on a block: ``with Watchdog(2.0, on_timeout): work()``.
    If ``work`` outlives the deadline, ``on_timeout()`` fires ONCE from
    a timer thread (the block itself keeps running — Python cannot
    safely preempt it — but its waiters get structured answers instead
    of a hang). ``fired`` says whether the deadline hit.

    ``clock`` (a ``time.monotonic``-shaped callable) makes the
    deadline CONTROLLABLE: with one injected, a watcher thread polls
    the clock (5 ms real-time granularity) instead of arming a
    wall-clock timer, so a test can hold time still — a compile
    running long on a slow CI host can no longer trip a deadline the
    test meant for the *modeled* clock — and advance it exactly when
    the scenario calls for the timeout (the deterministic fix for the
    host-speed-sensitive inverse-deadline flake). ``None`` (the
    default) keeps the zero-thread ``threading.Timer`` path."""

    _POLL_S = 0.005

    def __init__(self, deadline_s: Optional[float],
                 on_timeout: Callable[[], None],
                 clock: Optional[Callable[[], float]] = None):
        self.deadline_s = deadline_s
        self.on_timeout = on_timeout
        self.clock = clock
        self.fired = False
        self._timer: Optional[threading.Timer] = None
        self._stop: Optional[threading.Event] = None

    def _fire(self) -> None:
        self.fired = True
        try:
            self.on_timeout()
        except Exception:   # broken callback must not kill timer thread
            log.exception("watchdog on_timeout callback failed")

    def _watch(self, t0: float) -> None:
        while not self._stop.wait(self._POLL_S):
            if self.clock() - t0 >= self.deadline_s:
                self._fire()
                return

    def __enter__(self) -> "Watchdog":
        if self.deadline_s is None:
            return self
        if self.clock is None:
            self._timer = threading.Timer(self.deadline_s, self._fire)
            self._timer.daemon = True
            self._timer.start()
        else:
            self._stop = threading.Event()
            t = threading.Thread(target=self._watch,
                                 args=(self.clock(),),
                                 name="heat2d-watchdog", daemon=True)
            t.start()
        return self

    def __exit__(self, *exc) -> None:
        if self._timer is not None:
            self._timer.cancel()
        if self._stop is not None:
            self._stop.set()


def wait_for(predicate: Callable[[], bool],
             deadline_s: Optional[float], *,
             clock: Optional[Callable[[], float]] = None,
             poll: float = 0.01,
             sleep: Callable[[float], None] = time.sleep) -> bool:
    """THE bounded-poll deadline convention: poll ``predicate`` until
    it is truthy (True) or ``deadline_s`` expires on ``clock`` (False).

    Every hand-rolled ``deadline = monotonic() + t; while ...`` loop
    that guards a dispatch (supervisor ready-waits, rollout
    ready-waits, the mesh stall guard) routes through here so ONE
    ``Watchdog(clock=)`` owns deadline semantics — with an injected
    clock a test can freeze time (no wall-clock flakes on slow hosts)
    and advance it exactly when the scenario calls for the timeout.
    ``deadline_s=None`` waits forever (the predicate must win)."""
    if predicate():
        return True
    wd = Watchdog(deadline_s, lambda: None, clock=clock)
    with wd:
        while not wd.fired:
            if predicate():
                return True
            sleep(poll)
    # one last look: the predicate may have turned true in the same
    # poll window the deadline expired in — completion wins the race
    return bool(predicate())


class DegradedMode:
    """Consecutive-failure circuit breaker (closed -> open -> half-open).

    Thread-safe. ``allow()`` is the admission question: True while
    CLOSED; False while OPEN (shed); after ``cooldown`` seconds exactly
    one caller gets True as the HALF-OPEN probe and the rest stay shed
    until its verdict arrives via ``record_success``/``record_failure``
    — or until the probe token expires after one more ``cooldown``
    (a probe that hangs and never reports must not shed forever).
    """

    def __init__(self, threshold: int = 5, cooldown: float = 5.0,
                 registry=None, clock: Callable[[], float] = time.monotonic,
                 metric_prefix: str = "serve"):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooldown = cooldown
        self.registry = registry
        self.metric_prefix = metric_prefix
        self._clock = clock
        self._lock = AuditedLock("resil.degraded")
        self._failures = 0          # consecutive
        self._opened_at: Optional[float] = None
        self._probing = False
        self._probe_at: Optional[float] = None
        self.trips = 0

    # -- state --------------------------------------------------------- #

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._probing:
            return "half_open"
        if self._clock() - self._opened_at >= self.cooldown:
            return "half_open"
        return "open"

    # -- transitions --------------------------------------------------- #

    def allow(self) -> bool:
        with self._lock:
            s = self._state_locked()
            if s == "closed":
                return True
            if s == "half_open":
                now = self._clock()
                if (self._probing and self._probe_at is not None
                        and now - self._probe_at < self.cooldown):
                    return False    # a live probe holds the token
                # First probe — or the previous probe's verdict never
                # arrived (a hung launch, exactly the sickness the
                # breaker guards against): the token expires after one
                # cooldown, so a wedged probe cannot shed forever.
                self._probing = True
                self._probe_at = now
                self._gauge_locked()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._opened_at is not None:
                log.info("degraded mode cleared (probe succeeded)")
            self._opened_at = None
            self._probing = False
            self._gauge_locked()

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            reopen = self._probing
            self._probing = False
            if reopen or self._failures >= self.threshold:
                if self._opened_at is None:
                    self.trips += 1
                    log.warning(
                        "degraded mode TRIPPED after %d consecutive "
                        "failures (cooldown %.1fs)", self._failures,
                        self.cooldown)
                    if self.registry is not None:
                        self.registry.counter(
                            self.metric_prefix + "_breaker_trips_total")
                self._opened_at = self._clock()
            self._gauge_locked()

    def _gauge_locked(self) -> None:
        if self.registry is not None:
            self.registry.gauge(
                self.metric_prefix + "_degraded",
                0.0 if self._opened_at is None else 1.0)
