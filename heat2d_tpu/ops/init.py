"""Initial-condition generator (the reference's ``inidat``).

The reference computes ``u0[ix][iy] = ix*(nx-ix-1)*iy*(ny-iy-1)`` — zero on
all edges, peaked in the middle — in three copy-pasted places
(mpi_heat2Dn.c:242-248, grad1612_mpi_heat.c:163-168 in per-rank local
coordinates, grad1612_cuda_heat.cu:48-53 as a CUDA kernel). Here it is one
pure-jnp broadcast expression usable in either global or per-shard index
space: a shard passes its global top-left offset, exactly replacing the
reference's broadcast ``xs``/``ys`` offset tables (grad1612_mpi_heat.c:125-147)
with locally computed ``lax.axis_index`` offsets.

Numerics note: the C reference evaluates the product in ``int`` arithmetic,
which overflows int32 for grids ≳600² (undefined behavior in C); we evaluate
in float32 (exact for the small parity grids, well-defined everywhere).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def inidat(nx: int, ny: int, dtype=jnp.float32) -> jnp.ndarray:
    """Full-grid initial condition, identical to mpi_heat2Dn.c:242-248."""
    return inidat_block((nx, ny), nx, ny, 0, 0, dtype)


def inidat_block(block_shape: tuple[int, int], nx: int, ny: int,
                 x_offset, y_offset, dtype=jnp.float32) -> jnp.ndarray:
    """Initial condition for a local block at global offset
    (x_offset, y_offset).

    Equivalent to grad1612_mpi_heat.c:163-168 with ``xs``/``ys`` the global
    coordinates of the block's top-left cell. Offsets may be traced values
    (e.g. derived from ``lax.axis_index`` inside ``shard_map``).
    """
    bm, bn = block_shape
    ix = (lax.broadcasted_iota(dtype, (bm, bn), 0)
          + jnp.asarray(x_offset, dtype))
    iy = (lax.broadcasted_iota(dtype, (bm, bn), 1)
          + jnp.asarray(y_offset, dtype))
    nxf = jnp.asarray(nx, dtype)
    nyf = jnp.asarray(ny, dtype)
    return ix * (nxf - ix - 1) * iy * (nyf - iy - 1)
