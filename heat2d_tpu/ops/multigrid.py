"""Geometric multigrid V-cycle — the preconditioned iterative route.

The second implicit time-stepping route (``method="mg"``): instead of
splitting the Crank-Nicolson operator into 1-D tridiagonal factors
(``ops/tridiag.py``), solve the UNSPLIT 2-D system

    A u1 = (I - cx/2 dxx - cy/2 dyy) u1 = (I + cx/2 dxx + cy/2 dyy) u

per step with a fixed number of geometric V-cycles. No splitting
error (pure O(dt^2) CN), and the machinery is exactly what a
steady-state / convergence solve wants: A is an SPD shifted Laplacian,
so each V(nu1, nu2) cycle contracts the error by a grid-independent
factor — the step count to a fixed residual does not grow with
resolution, unlike every pointwise iteration.

The smoother REUSES the existing explicit stencil kernel: one damped-
Jacobi sweep on ``A u = rhs`` is algebraically

    u <- stencil_step(u, w*cx/(2D), w*cy/(2D)) + (w/D) * (rhs - u)

with ``D = 1 + cx + cy`` (the diagonal of A) — the same 5-point
update the explicit route saturates the VPU with, at rescaled
coefficients, plus an elementwise correction (docs/ALGORITHMS.md
derives this). Restriction is full-weighting, prolongation bilinear,
the coarse operator the rediscretized CN system (diffusion numbers
scale by 1/4 per level — c ~ 1/dx^2). Vertex-centered coarsening
applies while both dimensions are odd (2^k + 1 grids coarsen to
3x3); a dimension that cannot coarsen stops the hierarchy and the
coarsest level is relaxed to convergence with extra smoothing
sweeps.

Edges are held (clamped BC) at every level: the residual is zero on
edges, so coarse corrections vanish there by construction.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from heat2d_tpu.ops.stencil import stencil_step

#: default cycle shape: nu1/nu2 pre/post smoothing sweeps, coarsest-
#: level relaxation count, V-cycles per CN step.
MG_NU1 = 2
MG_NU2 = 2
MG_COARSE_SWEEPS = 24
MG_CYCLES = 2
MG_OMEGA = 0.8          # damped-Jacobi weight (4/5 is optimal for the
#                         pure 5-point Laplacian; A is easier)
MG_MIN_SIZE = 5         # stop coarsening below 5 points per axis


def _interior(x):
    return x[..., 1:-1, 1:-1]


def cn_apply(u, cx, cy):
    """``A u`` on the interior with held edges passed through:
    ``u - (cx/2) dxx u - (cy/2) dyy u`` (edge cells: identity rows)."""
    c = _interior(u)
    sx = u[2:, 1:-1] + u[:-2, 1:-1]
    sy = u[1:-1, 2:] + u[1:-1, :-2]
    new = c - 0.5 * cx * (sx - 2.0 * c) - 0.5 * cy * (sy - 2.0 * c)
    return u.at[1:-1, 1:-1].set(new)


def cn_rhs(u, cx, cy):
    """The CN right-hand side ``(I + cx/2 dxx + cy/2 dyy) u`` on the
    interior, edges passed through (the held boundary values the
    identity rows consume)."""
    c = _interior(u)
    sx = u[2:, 1:-1] + u[:-2, 1:-1]
    sy = u[1:-1, 2:] + u[1:-1, :-2]
    new = c + 0.5 * cx * (sx - 2.0 * c) + 0.5 * cy * (sy - 2.0 * c)
    return u.at[1:-1, 1:-1].set(new)


def residual(u, rhs, cx, cy):
    """``rhs - A u`` on the interior, ZERO on edges (identity rows are
    satisfied exactly once the edge values are held)."""
    r = rhs - cn_apply(u, cx, cy)
    return jnp.zeros_like(r).at[1:-1, 1:-1].set(_interior(r))


def smooth(u, rhs, cx, cy, omega: float = MG_OMEGA):
    """One damped-Jacobi sweep on ``A u = rhs`` — the existing
    explicit stencil kernel at rescaled coefficients plus an
    elementwise correction (module docstring). Edges held."""
    dinv = omega / (1.0 + cx + cy)
    s = stencil_step(u, 0.5 * cx * dinv, 0.5 * cy * dinv,
                     accum_dtype=None)
    corr = dinv * (_interior(rhs) - _interior(u))
    return s.at[1:-1, 1:-1].set(_interior(s) + corr)


def can_coarsen(nx: int, ny: int) -> bool:
    """Vertex-centered coarsening keeps the boundary in place only on
    odd sizes; both axes must stay >= MG_MIN_SIZE after halving."""
    return (nx % 2 == 1 and ny % 2 == 1
            and (nx - 1) // 2 + 1 >= MG_MIN_SIZE
            and (ny - 1) // 2 + 1 >= MG_MIN_SIZE)


def restrict(r):
    """Full-weighting restriction of a zero-edge residual onto the
    (nc, mc) = ((n+1)/2, (m+1)/2) coarse grid: the [1 2 1]^T[1 2 1]/16
    stencil at even fine points; coarse edges stay zero."""
    c = r[2:-2:2, 2:-2:2]
    n4 = (r[1:-3:2, 2:-2:2] + r[3:-1:2, 2:-2:2]
          + r[2:-2:2, 1:-3:2] + r[2:-2:2, 3:-1:2])
    d4 = (r[1:-3:2, 1:-3:2] + r[1:-3:2, 3:-1:2]
          + r[3:-1:2, 1:-3:2] + r[3:-1:2, 3:-1:2])
    interior = (4.0 * c + 2.0 * n4 + d4) / 16.0
    nc = (r.shape[0] - 1) // 2 + 1
    mc = (r.shape[1] - 1) // 2 + 1
    out = jnp.zeros((nc, mc), r.dtype)
    return out.at[1:-1, 1:-1].set(interior)


def prolong(e, shape):
    """Bilinear prolongation of a zero-edge coarse correction onto the
    fine grid ``shape``: coincident points copy, edge-midpoints
    average 2 neighbors, cell-centers average 4."""
    n, m = shape
    out = jnp.zeros(shape, e.dtype)
    out = out.at[::2, ::2].set(e)
    out = out.at[1::2, ::2].set(0.5 * (e[:-1, :] + e[1:, :]))
    out = out.at[::2, 1::2].set(0.5 * (e[:, :-1] + e[:, 1:]))
    out = out.at[1::2, 1::2].set(
        0.25 * (e[:-1, :-1] + e[:-1, 1:] + e[1:, :-1] + e[1:, 1:]))
    return out


def v_cycle(u, rhs, cx, cy, nu1: int = MG_NU1, nu2: int = MG_NU2):
    """One V(nu1, nu2) cycle on ``A u = rhs`` (static recursion —
    level shapes are compile-time constants, so the whole cycle traces
    into one program)."""
    for _ in range(nu1):
        u = smooth(u, rhs, cx, cy)
    nx, ny = u.shape
    if can_coarsen(nx, ny):
        r = residual(u, rhs, cx, cy)
        rc = restrict(r)
        # Rediscretized coarse operator: c ~ alpha*dt/dx^2, and the
        # coarse spacing doubles -> diffusion numbers quarter.
        ec = v_cycle(jnp.zeros_like(rc), rc, cx / 4.0, cy / 4.0,
                     nu1, nu2)
        u = u + prolong(ec, u.shape)
    else:
        for _ in range(MG_COARSE_SWEEPS):
            u = smooth(u, rhs, cx, cy)
    for _ in range(nu2):
        u = smooth(u, rhs, cx, cy)
    return u


def mg_solve(u0, rhs, cx, cy, cycles: int = MG_CYCLES):
    """``cycles`` V-cycles on ``A u = rhs`` from initial guess ``u0``."""
    u = u0
    for _ in range(cycles):
        u = v_cycle(u, rhs, cx, cy)
    return u


def mg_step(u, cx, cy, cycles: int = MG_CYCLES):
    """One Crank-Nicolson step at diffusion numbers (cx, cy), solved
    by ``cycles`` V-cycles from the previous state as initial guess
    (for smooth solutions the guess is O(dt) from the answer, so two
    cycles land far below the CN truncation error). Unconditionally
    stable; edges held."""
    cx = jnp.asarray(cx, u.dtype)
    cy = jnp.asarray(cy, u.dtype)
    return mg_solve(u, cn_rhs(u, cx, cy), cx, cy, cycles=cycles)


def mg_multi_step(u, steps: int, cx, cy, cycles: int = MG_CYCLES):
    """``steps`` CN/multigrid steps."""
    if steps == 0:
        return u
    return lax.fori_loop(0, steps,
                         lambda _, v: mg_step(v, cx, cy, cycles=cycles),
                         u, unroll=False)
