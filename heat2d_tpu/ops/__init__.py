from heat2d_tpu.ops.init import inidat, inidat_block
from heat2d_tpu.ops.stencil import (
    stencil_step,
    stencil_step_padded,
    stencil_step_var,
    residual_sq,
)
from heat2d_tpu.ops.stability import (
    check_explicit_stability,
    is_implicit,
    stability_limit,
)

__all__ = [
    "inidat",
    "inidat_block",
    "stencil_step",
    "stencil_step_padded",
    "stencil_step_var",
    "residual_sq",
    "check_explicit_stability",
    "is_implicit",
    "stability_limit",
]
