from heat2d_tpu.ops.init import inidat, inidat_block
from heat2d_tpu.ops.stencil import (
    stencil_step,
    stencil_step_padded,
    stencil_step_var,
    residual_sq,
)

__all__ = [
    "inidat",
    "inidat_block",
    "stencil_step",
    "stencil_step_padded",
    "stencil_step_var",
    "residual_sq",
]
