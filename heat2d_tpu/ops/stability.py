"""Explicit-scheme stability — the ONE home of the kx+ky <= 1/2 box.

The forward-Euler 5-point update ``u' = u + cx*dxx(u) + cy*dyy(u)`` is
stable iff ``cx + cy <= 1/2`` (von Neumann: the worst mode's
amplification factor is ``1 - 4cx - 4cy``, inside [-1, 1] exactly on
that box). Before this module the bound lived as magic numbers in
``diff/inverse.py`` (the projected-iterate clamp) and as an implicit
assumption everywhere else; it now lives here once:

- ``stability_limit(dx, dy)`` — the physical form: the largest stable
  ``alpha * dt`` for grid spacings (dx, dy). With the repo's
  dimensionless convention (``cx = alpha*dt/dx**2``) and dx = dy = 1
  this is the familiar 1/4 (i.e. cx = cy = 1/4, cx + cy = 1/2).
- ``check_explicit_stability(cx, cy)`` — the explicit routes' guard: a
  clear ``ConfigError`` naming the limit instead of a silently
  diverging run. IMPLICIT routes (method "adi"/"mg",
  ``ops/tridiag.py`` / ``ops/multigrid.py``) are unconditionally
  stable and deliberately never call it — dt is chosen by accuracy
  there, which is the whole algorithmic-speed story
  (docs/ALGORITHMS.md).
- ``KAPPA_MIN``/``KAPPA_MAX``/``project_stable`` — the inverse
  driver's projected-iterate box (isotropic kappa: kx = ky = kappa,
  so kappa <= 1/4; 0.24 leaves margin), re-exported by
  ``diff/inverse.py`` for back-compat.

jax-free on purpose: config validation and serving admission import
this on host-side paths.
"""

from __future__ import annotations

from heat2d_tpu.config import ConfigError
from heat2d_tpu.vocab import (ADVECTION_VELOCITY, IMPLICIT_METHODS,
                              REACTION_RATE)

#: The dimensionless coefficient-sum bound: cx + cy <= 1/2.
EXPLICIT_COEFF_LIMIT = 0.5

#: The 4th-order 9-point family's tighter box: the wide operator's
#: worst von Neumann mode has eigenvalue ``lam4(pi) = 16/3`` per axis
#: (vs the 5-point's 4), so ``|1 - (cx + cy) * 16/3| <= 1`` gives
#: ``cx + cy <= 3/8`` (problems/heat9; docs/PROBLEMS.md).
HEAT9_COEFF_LIMIT = 0.375

#: Stability box for projected diffusivity iterates (diff/inverse.py):
#: isotropic kappa (kx = ky) must satisfy 2*kappa <= 1/2; 0.24 leaves
#: margin below the exact 0.25, and the floor keeps the field physical
#: (kappa >= 0) and the solve sensitive to it.
KAPPA_MIN, KAPPA_MAX = 1e-4, 0.24

# IMPLICIT_METHODS is re-exported from vocab.py (the single-source
# method vocabulary): the A-stable time discretizations that skip the
# explicit stability box entirely.
IMPLICIT_METHODS = IMPLICIT_METHODS


def stability_limit(dx: float = 1.0, dy: float = 1.0) -> float:
    """The largest stable ``alpha * dt`` for the explicit scheme on
    spacings (dx, dy): ``1 / (2 * (dx**-2 + dy**-2))``. At dx = dy = 1
    this is 1/4 — equivalently the dimensionless box
    ``cx + cy <= 1/2`` with ``cx = alpha*dt/dx**2``."""
    if dx <= 0 or dy <= 0:
        raise ConfigError(f"grid spacings must be > 0, got dx={dx} "
                          f"dy={dy}")
    return 0.5 / (dx ** -2 + dy ** -2)


def is_implicit(method: str) -> bool:
    """True for unconditionally stable time-stepping routes — they
    skip ``check_explicit_stability`` by design."""
    return method in IMPLICIT_METHODS


def check_explicit_stability(cx: float, cy: float,
                             where: str = "explicit step") -> None:
    """Explicit routes' guard: raise a ``ConfigError`` NAMING the
    limit when (cx, cy) sit outside the stability box. Implicit
    routes must not call this (``is_implicit``)."""
    if cx < 0 or cy < 0:
        raise ConfigError(
            f"{where}: diffusivity coefficients must be >= 0, got "
            f"cx={cx} cy={cy}")
    if cx + cy > EXPLICIT_COEFF_LIMIT:
        raise ConfigError(
            f"{where}: cx + cy = {cx + cy:g} exceeds the explicit "
            f"stability limit cx + cy <= {EXPLICIT_COEFF_LIMIT} "
            f"(alpha*dt <= {stability_limit():g} at unit spacing — "
            f"ops/stability.py). Use an implicit method "
            f"(--method adi or mg), which is unconditionally stable, "
            f"or reduce the time step")


def check_heat9_stability(cx: float, cy: float,
                          where: str = "heat9 step") -> None:
    """heat9's guard — same contract as the 5-point check, tighter
    box: the 4th-order operator's worst-mode eigenvalue is 16/3 per
    axis, so the bound is ``cx + cy <= 3/8`` (NAMED in the error)."""
    if cx < 0 or cy < 0:
        raise ConfigError(
            f"{where}: diffusivity coefficients must be >= 0, got "
            f"cx={cx} cy={cy}")
    if cx + cy > HEAT9_COEFF_LIMIT:
        raise ConfigError(
            f"{where}: cx + cy = {cx + cy:g} exceeds the heat9 "
            f"(4th-order 9-point) stability limit cx + cy <= "
            f"{HEAT9_COEFF_LIMIT} (worst-mode eigenvalue 16/3 per "
            f"axis — ops/stability.py); reduce the time step")


def check_advdiff_stability(cx: float, cy: float,
                            where: str = "advdiff step") -> None:
    """advdiff's guard: the diffusion box PLUS the central-advection
    cell-Reynolds bounds ``vx**2 <= 2*cx`` and ``vy**2 <= 2*cy`` (the
    FTCS advection-diffusion condition; the family velocities are
    fixed constants, vocab.ADVECTION_VELOCITY). Both bounds NAMED."""
    check_explicit_stability(cx, cy, where=where)
    vx, vy = ADVECTION_VELOCITY
    for axis, v, c in (("x", vx, cx), ("y", vy, cy)):
        if v * v > 2.0 * c:
            raise ConfigError(
                f"{where}: advection CFL (cell-Reynolds) bound "
                f"v{axis}^2 <= 2*c{axis} violated: {v:g}^2 = "
                f"{v * v:g} > {2.0 * c:g} (family velocity "
                f"v{axis} = {v:g}, vocab.ADVECTION_VELOCITY — "
                f"ops/stability.py); increase c{axis} or use a "
                f"diffusivity of at least {v * v / 2.0:g}")


def check_reactdiff_stability(cx: float, cy: float,
                              where: str = "reactdiff step") -> None:
    """reactdiff's guard: the diffusion box PLUS the explicit
    reaction-rate bound ``r <= 1/2`` for the saturating source
    ``r*u/(1+u)``, whose Jacobian ``r/(1+u)^2`` is bounded by r at
    u = 0 (amplification 1 - 4cx - 4cy + r must stay in [-1, 1] with
    the diffusive worst mode: ``cx + cy <= 1/2`` and ``r <= 1/2``
    jointly suffice for u >= 0, where the source itself saturates at
    r). r is the fixed family constant (vocab.REACTION_RATE); the
    bound is checked so an out-of-tree family edit cannot silently
    destabilize."""
    check_explicit_stability(cx, cy, where=where)
    r = REACTION_RATE
    if r > 0.5:
        raise ConfigError(
            f"{where}: explicit reaction-rate bound r <= 1/2 "
            f"violated: r = {r:g} (vocab.REACTION_RATE — "
            f"ops/stability.py); reduce the reaction time step")


#: problem -> validation guard. heat5 and varcoef share the 5-point
#: box (varcoef's per-cell fields are bounded by (cx, cy) pointwise —
#: problems/kernels.varcoef_profiles).
_PROBLEM_CHECKS = {
    "heat5": check_explicit_stability,
    "varcoef": check_explicit_stability,
    "heat9": check_heat9_stability,
    "advdiff": check_advdiff_stability,
    "reactdiff": check_reactdiff_stability,
}


def check_problem_stability(problem: str, cx: float, cy: float,
                            where: str = "explicit step") -> None:
    """Per-family explicit-stability dispatch: every registered
    family's bound, NAMED in its error (the kx+ky <= 1/2 contract
    generalized). heat5 routes to ``check_explicit_stability``
    unchanged — identical error text on the default family."""
    try:
        check = _PROBLEM_CHECKS[problem]
    except KeyError:
        raise ConfigError(
            f"no stability bound registered for problem "
            f"{problem!r} (known: {tuple(_PROBLEM_CHECKS)})") from None
    check(cx, cy, where=where)


def project_stable(kappa):
    """Clamp an isotropic per-cell diffusivity field into the
    explicit stability box [KAPPA_MIN, KAPPA_MAX] — the inverse
    driver's per-iterate projection (jax import deferred: the clamp
    runs inside traced optimizer steps)."""
    import jax.numpy as jnp

    return jnp.clip(kappa, KAPPA_MIN, KAPPA_MAX)
