"""Explicit-scheme stability — the ONE home of the kx+ky <= 1/2 box.

The forward-Euler 5-point update ``u' = u + cx*dxx(u) + cy*dyy(u)`` is
stable iff ``cx + cy <= 1/2`` (von Neumann: the worst mode's
amplification factor is ``1 - 4cx - 4cy``, inside [-1, 1] exactly on
that box). Before this module the bound lived as magic numbers in
``diff/inverse.py`` (the projected-iterate clamp) and as an implicit
assumption everywhere else; it now lives here once:

- ``stability_limit(dx, dy)`` — the physical form: the largest stable
  ``alpha * dt`` for grid spacings (dx, dy). With the repo's
  dimensionless convention (``cx = alpha*dt/dx**2``) and dx = dy = 1
  this is the familiar 1/4 (i.e. cx = cy = 1/4, cx + cy = 1/2).
- ``check_explicit_stability(cx, cy)`` — the explicit routes' guard: a
  clear ``ConfigError`` naming the limit instead of a silently
  diverging run. IMPLICIT routes (method "adi"/"mg",
  ``ops/tridiag.py`` / ``ops/multigrid.py``) are unconditionally
  stable and deliberately never call it — dt is chosen by accuracy
  there, which is the whole algorithmic-speed story
  (docs/ALGORITHMS.md).
- ``KAPPA_MIN``/``KAPPA_MAX``/``project_stable`` — the inverse
  driver's projected-iterate box (isotropic kappa: kx = ky = kappa,
  so kappa <= 1/4; 0.24 leaves margin), re-exported by
  ``diff/inverse.py`` for back-compat.

jax-free on purpose: config validation and serving admission import
this on host-side paths.
"""

from __future__ import annotations

from heat2d_tpu.config import ConfigError

#: The dimensionless coefficient-sum bound: cx + cy <= 1/2.
EXPLICIT_COEFF_LIMIT = 0.5

#: Stability box for projected diffusivity iterates (diff/inverse.py):
#: isotropic kappa (kx = ky) must satisfy 2*kappa <= 1/2; 0.24 leaves
#: margin below the exact 0.25, and the floor keeps the field physical
#: (kappa >= 0) and the solve sensitive to it.
KAPPA_MIN, KAPPA_MAX = 1e-4, 0.24

#: Methods that skip the explicit stability box entirely (A-stable
#: time discretizations: Crank-Nicolson ADI, multigrid-solved CN).
IMPLICIT_METHODS = ("adi", "mg")


def stability_limit(dx: float = 1.0, dy: float = 1.0) -> float:
    """The largest stable ``alpha * dt`` for the explicit scheme on
    spacings (dx, dy): ``1 / (2 * (dx**-2 + dy**-2))``. At dx = dy = 1
    this is 1/4 — equivalently the dimensionless box
    ``cx + cy <= 1/2`` with ``cx = alpha*dt/dx**2``."""
    if dx <= 0 or dy <= 0:
        raise ConfigError(f"grid spacings must be > 0, got dx={dx} "
                          f"dy={dy}")
    return 0.5 / (dx ** -2 + dy ** -2)


def is_implicit(method: str) -> bool:
    """True for unconditionally stable time-stepping routes — they
    skip ``check_explicit_stability`` by design."""
    return method in IMPLICIT_METHODS


def check_explicit_stability(cx: float, cy: float,
                             where: str = "explicit step") -> None:
    """Explicit routes' guard: raise a ``ConfigError`` NAMING the
    limit when (cx, cy) sit outside the stability box. Implicit
    routes must not call this (``is_implicit``)."""
    if cx < 0 or cy < 0:
        raise ConfigError(
            f"{where}: diffusivity coefficients must be >= 0, got "
            f"cx={cx} cy={cy}")
    if cx + cy > EXPLICIT_COEFF_LIMIT:
        raise ConfigError(
            f"{where}: cx + cy = {cx + cy:g} exceeds the explicit "
            f"stability limit cx + cy <= {EXPLICIT_COEFF_LIMIT} "
            f"(alpha*dt <= {stability_limit():g} at unit spacing — "
            f"ops/stability.py). Use an implicit method "
            f"(--method adi or mg), which is unconditionally stable, "
            f"or reduce the time step")


def project_stable(kappa):
    """Clamp an isotropic per-cell diffusivity field into the
    explicit stability box [KAPPA_MIN, KAPPA_MAX] — the inverse
    driver's per-iterate projection (jax import deferred: the clamp
    runs inside traced optimizer steps)."""
    import jax.numpy as jnp

    return jnp.clip(kappa, KAPPA_MIN, KAPPA_MAX)
