"""Pallas/Mosaic TPU stencil kernels — the grad1612_cuda_heat.cu analogue.

The reference's CUDA path (grad1612_cuda_heat.cu:55-62 ``update`` kernel,
:82-85 ping-pong launch loop) maps one GPU thread to one cell and enqueues
two kernel launches per loop iteration from the host. The TPU-native design
inverts that: the *loop* lives on the device and the kernel owns *tiles*,
not cells:

- ``multi_step_vmem`` — whole-grid-in-VMEM kernel that runs many time steps
  per invocation (double buffering is a functional ``fori_loop`` carry in
  vector memory, replacing the CUDA pointer swap). One launch ≈ thousands
  of CUDA launches, zero HBM traffic between steps. Used when the grid fits
  the VMEM budget — covers the reference's own CUDA configs (640×1024 =
  2.5 MB).
- ``band_step`` — streaming one-step kernel for HBM-resident grids: the
  grid of programs walks row bands; each band reads its (bm, ny) block plus
  two precomputed neighbor-row strips (the intra-chip halo — the VMEM-tile
  analogue of the device-level ppermute halo), updates, and masks the
  global boundary in-register. Host-side strip extraction touches ~2 rows
  per band per step — negligible next to the band traffic itself.

Unlike the reference kernel, which computes per-cell in *double* (CUDA
promotes through the 2.0/0.1 literals — SURVEY.md Appendix B) and whose
result is vacuous anyway (A.1), these kernels compute in float32 (TPU has
no fast f64; parity tests run the golden model) and are verified against
the jnp golden model in interpreter mode and on-device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from heat2d_tpu.models import engine
from heat2d_tpu.ops.stencil import residual_sq

#: VMEM working-set budget for the resident kernel (carry + temporaries);
#: v5e has ~16 MB/core — stay well under.
VMEM_BUDGET_BYTES = 8 * 1024 * 1024


def _interpret() -> bool:
    """Interpreter mode off-TPU (tests on the virtual CPU mesh)."""
    return jax.default_backend() != "tpu"


def _step_value(u, cx, cy):
    """One clamped-boundary time step on an array *value* (in-kernel).

    Uses the FMA-friendly factoring ``(1-2cx-2cy)*u + cx*(N+S) + cy*(E+W)``
    — algebraically equal to the reference expression but mapping to 3
    multiply-adds on the VPU (+24% measured on the VPU-bound band kernel
    at 4096x4096; differs from the literal form only at f32-ulp level,
    same class as the f32-vs-double deviation the fast path already has —
    SURVEY.md Appendix B; the bitwise-parity paths use the literal form).
    Reassembles via concatenation rather than ``.at[].set`` — Mosaic has no
    scatter lowering, and concatenation of static slices vectorizes
    cleanly.
    """
    c = u[1:-1, 1:-1]
    k0 = 1.0 - 2.0 * cx - 2.0 * cy
    new = (k0 * c
           + cx * (u[2:, 1:-1] + u[:-2, 1:-1])
           + cy * (u[1:-1, 2:] + u[1:-1, :-2]))
    mid = jnp.concatenate([u[1:-1, :1], new, u[1:-1, -1:]], axis=1)
    return jnp.concatenate([u[:1, :], mid, u[-1:, :]], axis=0)


# --------------------------------------------------------------------- #
# Kernel A: VMEM-resident multi-step
# --------------------------------------------------------------------- #

def _vmem_kernel(u_ref, out_ref, *, steps, cx, cy):
    u = u_ref[:]
    u = lax.fori_loop(0, steps, lambda _, v: _step_value(v, cx, cy), u,
                      unroll=False)
    out_ref[:] = u


def fits_vmem(shape, dtype=jnp.float32) -> bool:
    nbytes = shape[0] * shape[1] * jnp.dtype(dtype).itemsize
    return 3 * nbytes <= VMEM_BUDGET_BYTES


def multi_step_vmem(u, steps: int, cx: float, cy: float):
    """Run ``steps`` time steps in one kernel, grid resident in VMEM."""
    kwargs = {}
    if pltpu is not None and not _interpret():
        kwargs = dict(
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM))
    return pl.pallas_call(
        functools.partial(_vmem_kernel, steps=steps, cx=cx, cy=cy),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        interpret=_interpret(),
        **kwargs)(u)


# --------------------------------------------------------------------- #
# Kernel B: streaming row-band one-step
# --------------------------------------------------------------------- #

def _band_kernel(up_ref, u_ref, dn_ref, out_ref, *, bm, nx, ny, cx, cy):
    i = pl.program_id(0)
    up = up_ref[:].reshape(1, ny)   # strips ride as (1, 1, ny) blocks
    dn = dn_ref[:].reshape(1, ny)
    ext = jnp.concatenate([up, u_ref[:], dn], axis=0)
    c = ext[1:-1, :]                       # the band itself, (bm, ny)
    north = ext[:-2, :]
    south = ext[2:, :]
    # FMA factoring, as in _step_value (algebraically equal, ulp-level).
    k0 = 1.0 - 2.0 * cx - 2.0 * cy
    newc = (k0 * c[:, 1:-1]
            + cx * (south[:, 1:-1] + north[:, 1:-1])
            + cy * (c[:, 2:] + c[:, :-2]))
    new = jnp.concatenate([c[:, :1], newc, c[:, -1:]], axis=1)
    # Global first/last row are boundary: keep (CUDA guard ix>0 && ix<NX-1,
    # grad1612_cuda_heat.cu:58).
    gi = i * bm + lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    keep = (gi == 0) | (gi == nx - 1)
    out_ref[:] = jnp.where(keep, c, new)


def pick_band_rows(nx: int, ny: int, dtype=jnp.float32,
                   target_bytes: int | None = None) -> int:
    """Largest divisor of nx whose (bm, ny) band fits the target size.

    The target shrinks for wide grids: the kernel's VMEM working set is
    several band-sized buffers plus per-step temporaries of the extended
    block, all proportional to the row size. Empirical envelope on v5e:
    2 MB bands compile at ny=4096 but not at ny=8192, where 1 MB bands
    do — hence the halved target once rows exceed 16 KB.
    """
    row_bytes = ny * jnp.dtype(dtype).itemsize
    if target_bytes is None:
        target_bytes = (1 if row_bytes > 16 * 1024 else 2) * 1024 * 1024
    cap = max(1, target_bytes // row_bytes)
    best = 1
    for bm in range(1, nx + 1):
        if nx % bm == 0 and bm <= cap:
            best = bm
    return best


def band_step(u, cx: float, cy: float, bm: int | None = None):
    """One time step of an HBM-resident grid via a row-band program grid."""
    nx, ny = u.shape
    if bm is None:
        bm = pick_band_rows(nx, ny, u.dtype)
    nblk = nx // bm
    zero_row = jnp.zeros((1, ny), u.dtype)
    # Neighbor-row strips: band i needs global rows i*bm-1 and (i+1)*bm.
    # Strided-slice extraction; edge bands get a zero row (never read into
    # the result — their first/last row is global boundary and kept).
    # Shaped (nblk, 1, ny) so each block is (1, 1, ny): Mosaic requires the
    # last two block dims to divide (8, 128) or equal the array dims.
    ups = jnp.concatenate([zero_row, u[bm - 1::bm][:nblk - 1]],
                          axis=0).reshape(nblk, 1, ny)
    dns = jnp.concatenate([u[bm::bm], zero_row],
                          axis=0).reshape(nblk, 1, ny)

    kwargs = {}
    mspace = {}
    if pltpu is not None and not _interpret():
        mspace = dict(memory_space=pltpu.VMEM)
    grid_spec = pl.GridSpec(
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((1, 1, ny), lambda i: (i, 0, 0), **mspace),
            pl.BlockSpec((bm, ny), lambda i: (i, 0), **mspace),
            pl.BlockSpec((1, 1, ny), lambda i: (i, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((bm, ny), lambda i: (i, 0), **mspace),
    )
    return pl.pallas_call(
        functools.partial(_band_kernel, bm=bm, nx=nx, ny=ny, cx=cx, cy=cy),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        **kwargs)(ups, u, dns)


# --------------------------------------------------------------------- #
# Kernel C: temporally-blocked band multi-step
# --------------------------------------------------------------------- #
#
# Kernel B is HBM-bound: every time step re-reads and re-writes the whole
# grid (2 x grid bytes/step). Temporal blocking amortizes that: each band
# carries a T-row halo strip on each side and advances T steps in VMEM per
# HBM sweep — traffic per step drops ~T x (plus a 2T/bm read overhead).
# Correctness of the halo depth: after s in-VMEM steps the outermost s rows
# of the extended band are stale, so the center bm rows are exact for
# s <= T. Stale data can never cross a *global* boundary row because the
# clamp mask is applied every internal step: row 0 / row nx-1 never update
# (the CUDA guard, grad1612_cuda_heat.cu:58), so garbage in the
# out-of-domain strip rows of edge bands is firewalled at the boundary.

def _band_multi_kernel(up_ref, u_ref, dn_ref, out_ref, *,
                       bm, tsteps, nx, ny, cx, cy):
    i = pl.program_id(0)
    ext = jnp.concatenate([up_ref[0], u_ref[:], dn_ref[0]], axis=0)
    # Global row ids of ext rows; <=0 also covers out-of-domain strip rows.
    gi = (i * bm - tsteps
          + lax.broadcasted_iota(jnp.int32, (bm + 2 * tsteps, 1), 0))
    keep = (gi <= 0) | (gi >= nx - 1)

    def one(_, v):
        return jnp.where(keep, v, _step_value(v, cx, cy))

    ext = lax.fori_loop(0, tsteps, one, ext, unroll=False)
    out_ref[:] = ext[tsteps:-tsteps]


def band_multi_step(u, tsteps: int, cx: float, cy: float,
                    bm: int | None = None):
    """Advance ``tsteps`` time steps in one sweep of row-band programs."""
    nx, ny = u.shape
    if bm is None:
        bm = pick_band_rows(nx, ny, u.dtype)
    if tsteps < 1 or bm <= 2 * tsteps:
        # Not enough band depth to amortize — fall back to stepwise.
        out = u
        for _ in range(tsteps):
            out = band_step(out, cx, cy, bm=bm)
        return out
    nblk = nx // bm
    t = tsteps
    zeros = jnp.zeros((1, t, ny), u.dtype)
    blocks = u.reshape(nblk, bm, ny)
    # Band i's halo strips: global rows [i*bm - t, i*bm) and
    # [(i+1)*bm, (i+1)*bm + t). Edge bands get zeros — firewalled by the
    # per-step boundary mask above, never read into the kept result.
    ups = jnp.concatenate([zeros, blocks[:-1, bm - t:, :]], axis=0)
    dns = jnp.concatenate([blocks[1:, :t, :], zeros], axis=0)

    kwargs = {}
    mspace = {}
    if pltpu is not None and not _interpret():
        mspace = dict(memory_space=pltpu.VMEM)
    grid_spec = pl.GridSpec(
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((1, t, ny), lambda i: (i, 0, 0), **mspace),
            pl.BlockSpec((bm, ny), lambda i: (i, 0), **mspace),
            pl.BlockSpec((1, t, ny), lambda i: (i, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((bm, ny), lambda i: (i, 0), **mspace),
    )
    return pl.pallas_call(
        functools.partial(_band_multi_kernel, bm=bm, tsteps=t,
                          nx=nx, ny=ny, cx=cx, cy=cy),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        **kwargs)(ups, u, dns)


#: Default temporal depth for HBM-resident grids. Bounded by VMEM (the
#: band needs bm > 2T rows) and by diminishing returns once traffic per
#: step is ~grid_bytes/T; 8 cuts HBM traffic ~8x.
DEFAULT_TSTEPS = 8


def band_chunk(u, n: int, cx: float, cy: float,
               tsteps: int = DEFAULT_TSTEPS, bm: int | None = None):
    """Advance ``n`` (static) steps: full T-sweeps plus a remainder sweep."""
    nsweeps, rem = divmod(n, tsteps)
    if nsweeps:
        u = lax.fori_loop(
            0, nsweeps,
            lambda _, v: band_multi_step(v, tsteps, cx, cy, bm=bm), u,
            unroll=False)
    if rem:
        u = band_multi_step(u, rem, cx, cy, bm=bm)
    return u


# --------------------------------------------------------------------- #
# Engine integration
# --------------------------------------------------------------------- #

def make_single_chip_runner(config):
    """Compiled ``u0 -> (u_final, steps_done)`` for mode='pallas'.

    Fixed-step runs on a VMEM-sized grid execute as ONE kernel invocation;
    convergence runs chunk INTERVAL steps per invocation so the residual
    check (implemented correctly, unlike the reference — SURVEY.md A.2)
    stays on-device between chunks. HBM-sized grids stream band-kernel
    steps under lax.fori/while exactly like the golden engine.
    """
    cx, cy = config.cx, config.cy
    nx, ny = config.nxprob, config.nyprob
    resident = fits_vmem((nx, ny))

    if resident:
        def step(u):
            return multi_step_vmem(u, 1, cx, cy)

        def chunk(u, n):  # n is a static Python int: baked into the kernel
            return multi_step_vmem(u, n, cx, cy)
    else:
        def step(u):
            return band_step(u, cx, cy)

        def chunk(u, n):  # temporally-blocked sweeps (~T x less HBM traffic)
            return band_chunk(u, n, cx, cy)

    def run(u):
        residual = lambda a, b: residual_sq(a, b)  # noqa: E731
        if config.convergence:
            return engine.run_convergence_chunked(
                chunk, step, residual, u,
                config.steps, config.interval, config.sensitivity)
        # Fixed-step: resident grids run as ONE kernel invocation;
        # HBM grids as temporally-blocked sweeps.
        u = chunk(u, config.steps)
        return u, jnp.asarray(config.steps, jnp.int32)

    return jax.jit(run)


def make_padded_kernel(config):
    """Per-shard kernel for mode='hybrid': one step on a halo-padded
    (bm+2, bn+2) block, returning the updated (bm, bn) interior — the
    drop-in replacement for ops.stencil.stencil_step_padded inside the
    shard_map step (caller masks the global boundary)."""
    cx, cy = config.cx, config.cy

    def kernel(p_ref, out_ref):
        p = p_ref[:]
        c = p[1:-1, 1:-1]
        out_ref[:] = (c
                      + cx * (p[2:, 1:-1] + p[:-2, 1:-1] - 2.0 * c)
                      + cy * (p[1:-1, 2:] + p[1:-1, :-2] - 2.0 * c))

    def padded_step(padded, cx_unused=None, cy_unused=None):
        bm, bn = padded.shape[0] - 2, padded.shape[1] - 2
        kwargs = {}
        if pltpu is not None and not _interpret():
            kwargs = dict(
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM))
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((bm, bn), padded.dtype),
            interpret=_interpret(),
            **kwargs)(padded)

    return padded_step
